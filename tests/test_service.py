"""Service-layer tests: middleware pipeline, batcher windows, and the full
end-to-end path request → broker → middleware → batcher → engine → response
(SURVEY.md §4 "integration-test request→response through the full
middleware+batcher+kernel path")."""

import asyncio
import json
import time

import pytest

from matchmaking_tpu.config import (
    AuthConfig,
    BatcherConfig,
    BrokerConfig,
    Config,
    EngineConfig,
    QueueConfig,
)
from matchmaking_tpu.service.app import MatchmakingApp
from matchmaking_tpu.service.batcher import Batcher
from matchmaking_tpu.service.broker import Delivery, InProcBroker, Properties
from matchmaking_tpu.service.client import MatchmakingClient
from matchmaking_tpu.testing.drain import fully_drained
from matchmaking_tpu.service.middleware import (
    AuthMiddleware,
    DecodeMiddleware,
    MessageContext,
    MiddlewareReject,
    Pipeline,
)


def tiny_cfg(backend="tpu", queues=None, **kw):
    return Config(
        queues=queues or (QueueConfig(rating_threshold=100.0),),
        engine=EngineConfig(backend=backend, pool_capacity=256, pool_block=64,
                            batch_buckets=(8, 32), top_k=4),
        batcher=BatcherConfig(max_batch=8, max_wait_ms=10.0),
        **kw,
    )


def _delivery(body: bytes, headers=None, reply_to="r.q", corr="c1"):
    return Delivery(body=body, properties=Properties(reply_to=reply_to,
                    correlation_id=corr, headers=headers or {}),
                    queue="q", delivery_tag=1)


# ---- middleware -----------------------------------------------------------


async def test_decode_middleware_sets_request():
    ctx = MessageContext(_delivery(b'{"id":"p","rating":1500}'), queue="q")
    await Pipeline([DecodeMiddleware()]).run(ctx)
    assert ctx.request is not None and ctx.request.id == "p"
    assert ctx.request.reply_to == "r.q" and ctx.request.queue == "q"
    assert ctx.request.enqueued_at == pytest.approx(ctx.received_at)


async def test_decode_middleware_rejects_bad_payload():
    ctx = MessageContext(_delivery(b"garbage"), queue="q")
    with pytest.raises(MiddlewareReject) as ei:
        await Pipeline([DecodeMiddleware()]).run(ctx)
    assert ei.value.code == "bad_json"


async def test_auth_middleware_static():
    mw = AuthMiddleware(AuthConfig(mode="static", static_secret="sekrit"))
    ok = MessageContext(_delivery(b"{}", headers={"authorization": "sekrit-abc"}), queue="q")
    ran = []

    async def nxt():
        ran.append(1)

    await mw.call(ok, nxt)
    assert ran == [1]
    bad = MessageContext(_delivery(b"{}", headers={"authorization": "wrong"}), queue="q")
    with pytest.raises(MiddlewareReject) as ei:
        await mw.call(bad, nxt)
    assert ei.value.code == "unauthorized"


async def test_auth_middleware_rpc_roundtrip():
    broker = InProcBroker(BrokerConfig())

    async def auth_service(d):
        verdict = b"ok" if d.body == b"good" else b"denied"
        broker.publish(d.properties.reply_to, verdict,
                       Properties(correlation_id=d.properties.correlation_id))
        broker.ack(tag, d.delivery_tag)

    tag = broker.basic_consume("auth.token.verify", auth_service)
    mw = AuthMiddleware(AuthConfig(mode="rpc"), broker)

    async def nxt():
        pass

    await mw.call(MessageContext(_delivery(b"{}", headers={"authorization": "good"}), queue="q"), nxt)
    with pytest.raises(MiddlewareReject):
        await mw.call(MessageContext(_delivery(b"{}", headers={"authorization": "evil"}), queue="q"), nxt)
    broker.close()


# ---- batcher --------------------------------------------------------------


async def test_batcher_size_trigger():
    windows = []

    async def flush(w):
        windows.append(list(w))

    b = Batcher(BatcherConfig(max_batch=4, max_wait_ms=10_000.0), flush)
    for i in range(4):
        b.submit(i)
    await asyncio.sleep(0.05)
    assert windows == [[0, 1, 2, 3]]  # size fired despite huge wait
    await b.close()


async def test_batcher_time_trigger():
    windows = []

    async def flush(w):
        windows.append(list(w))

    b = Batcher(BatcherConfig(max_batch=1000, max_wait_ms=20.0), flush)
    b.submit("only")
    t0 = time.perf_counter()
    while not windows:
        assert time.perf_counter() - t0 < 1.0
        await asyncio.sleep(0.005)
    assert windows == [["only"]]
    assert time.perf_counter() - t0 < 0.5
    await b.close()


async def test_batcher_serializes_windows():
    active = [0]
    overlap = []

    async def flush(w):
        active[0] += 1
        overlap.append(active[0])
        await asyncio.sleep(0.02)
        active[0] -= 1

    b = Batcher(BatcherConfig(max_batch=2, max_wait_ms=5.0), flush)
    for i in range(10):
        b.submit(i)
    await asyncio.sleep(0.3)
    assert max(overlap) == 1  # windows never overlap (atomicity)
    await b.close()


# ---- end-to-end -----------------------------------------------------------


async def test_e2e_two_players_match():
    app = MatchmakingApp(tiny_cfg())
    await app.start()
    client = MatchmakingClient(app.broker, "matchmaking.search")
    a, b = (client.submit({"id": "alice", "rating": 1500}),
            client.submit({"id": "bob", "rating": 1540}))
    ra = await client.next_response(a, timeout=15.0)
    rb = await client.next_response(b, timeout=15.0)
    # Both arrive in one window → immediate match (no queued ack first).
    assert {ra.status, rb.status} == {"matched"}
    assert ra.match.match_id == rb.match.match_id
    assert set(ra.match.players) == {"alice", "bob"}
    await app.stop()


async def test_e2e_queued_then_matched_later():
    app = MatchmakingApp(tiny_cfg())
    await app.start()
    client = MatchmakingClient(app.broker, "matchmaking.search")
    a = client.submit({"id": "alice", "rating": 1500})
    ra = await client.next_response(a, timeout=15.0)
    assert ra.status == "queued"
    await asyncio.sleep(0.05)  # next window
    b = client.submit({"id": "bob", "rating": 1520})
    ra2 = await client.next_response(a, timeout=15.0)
    rb = await client.next_response(b, timeout=15.0)
    assert ra2.status == "matched" and rb.status == "matched"
    assert ra2.match.match_id == rb.match.match_id
    await app.stop()


async def test_e2e_malformed_payload_gets_error_response():
    app = MatchmakingApp(tiny_cfg())
    await app.start()
    import uuid

    reply = f"amq.gen-{uuid.uuid4().hex}"
    app.broker.publish("matchmaking.search", b"not json",
                       Properties(reply_to=reply, correlation_id="x"))
    d = await app.broker.get(reply, timeout=15.0)
    resp = json.loads(d.body)
    assert resp["status"] == "error" and resp["error"]["code"] == "bad_json"
    await app.stop()


async def test_e2e_party_rejected_on_1v1_queue():
    app = MatchmakingApp(tiny_cfg())
    await app.start()
    client = MatchmakingClient(app.broker, "matchmaking.search")
    r = client.submit({"id": "lead", "rating": 1500,
                       "party": [{"id": "m2", "rating": 1510}]})
    resp = await client.next_response(r, timeout=15.0)
    assert resp.status == "error" and resp.error_code == "party_not_supported"
    await app.stop()


async def test_e2e_auth_static_rejects_without_token():
    cfg = tiny_cfg(auth=AuthConfig(mode="static", static_secret="tok"))
    app = MatchmakingApp(cfg)
    await app.start()
    good = MatchmakingClient(app.broker, "matchmaking.search", auth_token="tok-1")
    bad = MatchmakingClient(app.broker, "matchmaking.search")
    rb = bad.submit({"id": "evil", "rating": 1500})
    resp = await bad.next_response(rb, timeout=15.0)
    assert resp.status == "error" and resp.error_code == "unauthorized"
    rg = good.submit({"id": "nice", "rating": 1500})
    resp = await good.next_response(rg, timeout=15.0)
    assert resp.status == "queued"
    await app.stop()


async def test_e2e_multi_queue_partitioning():
    # BASELINE config #2: separate queues per game mode.
    queues = (QueueConfig(name="mm.ranked", game_mode="ranked", rating_threshold=100),
              QueueConfig(name="mm.casual", game_mode="casual", rating_threshold=100))
    app = MatchmakingApp(tiny_cfg(queues=queues))
    await app.start()
    client = MatchmakingClient(app.broker, "mm.ranked")
    r1 = client.submit({"id": "a", "rating": 1500}, queue="mm.ranked")
    r2 = client.submit({"id": "b", "rating": 1510}, queue="mm.casual")
    ra = await client.next_response(r1, timeout=15.0)
    rb = await client.next_response(r2, timeout=15.0)
    # Different queues must NOT match each other.
    assert ra.status == "queued" and rb.status == "queued"
    r3 = client.submit({"id": "c", "rating": 1505}, queue="mm.ranked")
    rc = await client.next_response(r3, timeout=15.0)
    ra2 = await client.next_response(r1, timeout=15.0)
    assert rc.status == "matched" and ra2.status == "matched"
    assert set(rc.match.players) == {"a", "c"}
    await app.stop()


async def test_e2e_request_timeout_response():
    queues = (QueueConfig(rating_threshold=10.0, request_timeout_s=0.2),)
    app = MatchmakingApp(tiny_cfg(queues=queues))
    await app.start()
    client = MatchmakingClient(app.broker, "matchmaking.search")
    r = client.submit({"id": "lonely", "rating": 1500})
    resp = await client.next_response(r, timeout=15.0)
    assert resp.status == "queued"
    resp = await client.next_response(r, timeout=15.0)
    assert resp.status == "timeout"
    assert app.runtime("matchmaking.search").engine.pool_size() == 0
    await app.stop()


async def test_e2e_engine_crash_recovers_from_mirror(monkeypatch):
    # SURVEY.md §5 failure recovery: engine dies mid-window → window is
    # nacked/redelivered, engine is revived from the host mirror, and the
    # waiting player is still matchable afterwards.
    app = MatchmakingApp(tiny_cfg())
    await app.start()
    client = MatchmakingClient(app.broker, "matchmaking.search")
    a = client.submit({"id": "alice", "rating": 1500})
    ra = await client.next_response(a, timeout=15.0)
    assert ra.status == "queued"

    rt = app.runtime("matchmaking.search")
    # The columnar flush enters through search_columns_async; crash there.
    # Revive replaces the engine object, so only the first call explodes.

    def exploding(cols, now):
        raise RuntimeError("injected engine crash")

    monkeypatch.setattr(rt.engine, "search_columns_async", exploding)
    b = client.submit({"id": "bob", "rating": 1520})
    rb = await client.next_response(b, timeout=3.0)
    ra2 = await client.next_response(a, timeout=3.0)
    assert rb.status == "matched" and ra2.status == "matched"
    assert set(rb.match.players) == {"alice", "bob"}
    assert app.metrics.counters.get("engine_crashes") == 1
    await app.stop()


async def test_e2e_under_broker_faults():
    # Drop/dup injection: at-least-once + idempotent enqueue must still
    # produce exactly-once match results.
    cfg = tiny_cfg(broker=BrokerConfig(drop_prob=0.2, dup_prob=0.2, max_redelivery=20))
    app = MatchmakingApp(cfg)
    await app.start()
    client = MatchmakingClient(app.broker, "matchmaking.search")
    n = 16
    replies = [client.submit({"id": f"p{i}", "rating": 1500 + (i % 4)}) for i in range(n)]
    results = await asyncio.gather(*[_await_terminal(client, r) for r in replies])
    matched = [r for r in results if r and r.status == "matched"]
    assert len(matched) == n
    # No player may appear in two different matches.
    seen = {}
    for r in matched:
        for pid in r.match.players:
            assert seen.setdefault(pid, r.match.match_id) == r.match.match_id
    await app.stop()


async def _await_terminal(client, reply_to, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    last = None
    while asyncio.get_event_loop().time() < deadline:
        resp = await client.next_response(reply_to, timeout=0.5)
        if resp is not None:
            last = resp
            if resp.status != "queued":
                return resp
    return last


async def test_e2e_duplicate_delivery_never_double_matches():
    # dup_prob=1.0: EVERY request is delivered twice. Reading every response
    # on every reply queue, each player must see exactly one match_id.
    cfg = tiny_cfg(broker=BrokerConfig(dup_prob=1.0))
    app = MatchmakingApp(cfg)
    await app.start()
    client = MatchmakingClient(app.broker, "matchmaking.search")
    n = 8
    replies = {f"p{i}": client.submit({"id": f"p{i}", "rating": 1500 + i}) for i in range(n)}
    # Deterministic drain (the PR 2 soak pattern, ISSUE 15 satellite): the
    # old fixed 0.3 s sleep raced the duplicate redeliveries on the 1-core
    # box (PR 14 reproduced the flake on the unmodified tree). The break
    # condition mirrors the assertions below — every player matched AND
    # nothing is buffered at ANY stage, so every duplicate has been
    # consumed and its replay response published (the same predicate the
    # crash-soak quiesce polls; extended in one place as stages grow).
    rt = app.runtime("matchmaking.search")
    for _ in range(400):
        await asyncio.sleep(0.025)
        if fully_drained(app, rt, "matchmaking.search", n):
            break
    match_ids = {}
    for pid, reply_to in replies.items():
        while True:
            resp = await client.next_response(reply_to, timeout=0.2)
            if resp is None:
                break
            if resp.status == "matched":
                match_ids.setdefault(pid, set()).add(resp.match.match_id)
    for pid, ids in match_ids.items():
        assert len(ids) == 1, f"{pid} saw {len(ids)} distinct matches"
    assert len(match_ids) == n
    assert app.metrics.counters.get("players_matched") == n  # engine saw each once
    await app.stop()


async def test_app_stop_with_pending_window_is_clean():
    # Items still sitting in the batcher at stop(): shutdown must not crash
    # and must flush or requeue them.
    cfg = tiny_cfg()
    cfg = Config(queues=cfg.queues, engine=cfg.engine,
                 batcher=BatcherConfig(max_batch=64, max_wait_ms=10_000.0))
    app = MatchmakingApp(cfg)
    await app.start()
    client = MatchmakingClient(app.broker, "matchmaking.search")
    a = client.submit({"id": "alice", "rating": 1500})
    b = client.submit({"id": "bob", "rating": 1510})
    await asyncio.sleep(0.05)  # delivered into the batcher; window still open
    await app.stop()  # must not raise; close() flushes the pending window
    ra = await client.next_response(a, timeout=1.0)
    rb = await client.next_response(b, timeout=1.0)
    assert ra is not None and rb is not None
    assert {ra.status, rb.status} == {"matched"}


async def test_reply_queues_do_not_leak():
    app = MatchmakingApp(tiny_cfg())
    await app.start()
    client = MatchmakingClient(app.broker, "matchmaking.search")
    base = len(app.broker._queues)
    for i in range(0, 20, 2):
        r1 = await client.search_until_matched({"id": f"a{i}", "rating": 1500}, timeout=15.0)
        assert r1.status in ("matched", "queued", "timeout")
    # search_until_matched deletes its reply queue; only the odd leftovers
    # from pairing (none here: players match in pairs a{i}/a{i+1}? actually
    # sequential singles pile up) — just assert no growth beyond the waiting
    # players still being matched.
    assert len(app.broker._queues) <= base + 1
    await app.stop()


async def test_redelivery_preserves_wait_clock(monkeypatch):
    # A crashed window's redelivered request must keep its original
    # enqueued_at (timeout sweeper / widening restart otherwise).
    app = MatchmakingApp(tiny_cfg())
    await app.start()
    rt = app.runtime("matchmaking.search")
    seen_enqueued = []

    def crashing(cols, now):
        # Record the wait clock the engine would have seen, then crash
        # (revive replaces the engine object, so only this call explodes).
        seen_enqueued.extend(cols.enqueued_at.tolist())
        raise RuntimeError("crash before matching")

    monkeypatch.setattr(rt.engine, "search_columns_async", crashing)
    client = MatchmakingClient(app.broker, "matchmaking.search")
    r = client.submit({"id": "alice", "rating": 1500})
    resp = await client.next_response(r, timeout=3.0)
    assert resp is not None and resp.status == "queued"
    # The crash revived the engine (new object, real search), so the
    # redelivered copy lives in the NEW engine's pool: its enqueued_at must
    # equal the original receive time, not the redelivery time.
    assert len(seen_enqueued) == 1
    waiting = rt.engine.waiting()
    assert len(waiting) == 1
    assert waiting[0].enqueued_at == pytest.approx(seen_enqueued[0], abs=1e-6)
    await app.stop()
