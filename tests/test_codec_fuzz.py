"""Native-codec parity fuzz (ISSUE 9 satellite; ``codec`` marker).

A seeded generator round-trips random requests and responses through the
native batch codec vs the Python contract module — the semantic source of
truth. The bar:

- **decode**: every native-OK row field-equal to ``decode_request``; every
  error row maps to the same ContractError class; every NEEDS_PYTHON row
  must decode successfully in Python (the fallback path cannot dead-end);
- **encode**: every native body BYTE-identical to ``encode_response`` —
  including the float formatting (``repr(round(x, k))``: shortest
  round-trip digits, half-even decimal rounding, CPython's
  fixed-vs-scientific threshold) — and every None row (non-ASCII /
  non-finite / NUL) re-encodable through the Python contract.

scripts/check.sh runs this by marker after rebuilding libmmcodec.so from
source, so CI never depends on the checked-in binary.
"""

import json
import math
import random

import numpy as np
import pytest

from matchmaking_tpu.native import codec
from matchmaking_tpu.service.contract import (
    ANY,
    ContractError,
    MatchResult,
    SearchResponse,
    decode_request,
    encode_response,
)

pytestmark = [
    pytest.mark.codec,
    pytest.mark.skipif(not codec.available(),
                       reason="native codec unavailable (no g++?)"),
]

#: Corpus size per direction; ~milliseconds per thousand rows.
N = 1500


def _rand_float(rng: random.Random) -> float:
    """Floats spanning the formats repr can produce: subnormal-ish tiny,
    fixed-range, integral, huge (scientific), negative, decimal-tie
    values, and exact binary fractions."""
    k = rng.random()
    if k < 0.18:
        return rng.uniform(0.0, 1.0)
    if k < 0.36:
        return rng.uniform(0.0, 1e5)
    if k < 0.46:
        return float(rng.randint(0, 10**6))
    if k < 0.56:
        return rng.uniform(0.0, 1e-4)
    if k < 0.66:
        return rng.uniform(1e10, 1e18)
    if k < 0.76:
        return -rng.uniform(0.0, 1e4)
    if k < 0.86:
        return rng.randint(0, 10**6) / 2.0 ** rng.randint(0, 12)
    return rng.choice([0.0, -0.0, 0.0625, 2.675, 0.1 + 0.2, 1e16,
                       9999999999999998.0, 1e-5, 1.0005, 2.5e-3])


def _rand_id(rng: random.Random) -> str:
    pool = ("plain", 'quo"te', "back\\slash", "tab\there", "nl\ninside",
            "ctl\x01\x1f", "sp ace", "unicode-é", "emoji-🎮", "")
    if rng.random() < 0.7:
        return f"p{rng.randrange(10**6)}"
    return rng.choice(pool) + str(rng.randrange(100))


# ---------------------------------------------------------------------------
# decode: requests


def test_fuzz_decode_requests_vs_contract():
    rng = random.Random(20260803)
    bodies: list[bytes] = []
    for i in range(N):
        roll = rng.random()
        if roll < 0.08:
            # Malformed/garbled payloads.
            bodies.append(rng.choice([
                b"not json", b"[1,2]", b'{"rating":1}', b'{"id":"x"}',
                b'{"id":"x","rating":"hi"}', b'{"id":7,"rating":1}',
                b'{"id":"x","rating":+5}', b'{"id":"x","rating":5.}',
                b'{"id":"x","rating":1e7}',
                b'{"id":"x","rating":1,"rating_deviation":-2}',
                b'{"id":"x","rating":1,"rating_threshold":0}',
            ]))
            continue
        payload: dict = {"id": _rand_id(rng),
                         "rating": _rand_float(rng) % 9e4}
        if rng.random() < 0.5:
            payload["rating_deviation"] = rng.uniform(0.0, 350.0)
        if rng.random() < 0.4:
            payload["region"] = rng.choice(["eu", "na", "apac", "*"])
        if rng.random() < 0.4:
            payload["game_mode"] = rng.choice(["ranked", "casual"])
        if rng.random() < 0.3:
            payload["rating_threshold"] = rng.uniform(0.5, 400.0)
        if rng.random() < 0.1:
            payload["roles"] = ["tank", "dps"]
        if rng.random() < 0.1:
            payload["party"] = [{"id": f"q{i}", "rating": 1500}]
        if rng.random() < 0.15:
            payload["junk"] = {"nested": [1, None, {"a": "b"}]}
        bodies.append(json.dumps(payload).encode())
    out = codec.decode_batch(bodies)
    assert out is not None
    ids, rating, rd, thr, regions, modes, status = out
    n_ok = n_py = 0
    for i, body in enumerate(bodies):
        st = int(status[i])
        try:
            py = decode_request(body)
        except ContractError as err:
            # Python rejects: native must reject with the same class, or
            # punt to Python (which reports the same error downstream).
            assert st != codec.OK, body
            if st != codec.NEEDS_PYTHON:
                assert codec.error_code(st) == err.code, body
            continue
        # Python accepts: native must accept with equal fields, or punt.
        assert st in (codec.OK, codec.NEEDS_PYTHON), body
        if st == codec.NEEDS_PYTHON:
            n_py += 1
            continue
        n_ok += 1
        assert ids[i] == py.id
        assert rating[i] == pytest.approx(py.rating, rel=1e-6, abs=1e-6)
        assert rd[i] == pytest.approx(py.rating_deviation, rel=1e-6)
        if py.rating_threshold is None:
            assert math.isnan(thr[i])
        else:
            assert thr[i] == pytest.approx(py.rating_threshold, rel=1e-6)
        assert (regions[i] or ANY) == py.region
        assert (modes[i] or ANY) == py.game_mode
    assert n_ok > N // 2  # the fast path must carry the bulk of the corpus


def _concat(bodies: list[bytes]):
    buf = b"".join(bodies)
    off = np.zeros(len(bodies) + 1, np.int64)
    np.cumsum(np.fromiter((len(b) for b in bodies), np.int64, len(bodies)),
              out=off[1:])
    return buf, off


def test_fuzz_decode_concat_matches_pointer_decoder():
    """The concat decoder (ISSUE 12 — the consume_batch body layout) must
    agree with the per-pointer decoder row for row over the same corpus:
    same statuses, same fields, same NEEDS_PYTHON/reject classes — it IS
    the same row decode, fed from the encoders' arena+offset layout."""
    rng = random.Random(20260804)
    bodies: list[bytes] = []
    for i in range(N):
        roll = rng.random()
        if roll < 0.10:
            bodies.append(rng.choice([
                b"", b"{", b"not json", b'{"id":"x","rating":+5}',
                b'{"id":"x","rating":1e7}', b'[1]', b'{"rating":1}',
                b'{"id":"x","rating":5.}',
            ]))
            continue
        payload: dict = {"id": _rand_id(rng),
                         "rating": _rand_float(rng) % 9e4}
        if rng.random() < 0.4:
            payload["region"] = rng.choice(["eu", "na", "*"])
        if rng.random() < 0.3:
            payload["rating_threshold"] = rng.uniform(0.5, 400.0)
        if rng.random() < 0.1:
            payload["party"] = [{"id": f"q{i}", "rating": 1500}]
        bodies.append(json.dumps(payload).encode())
    ref = codec.decode_batch(bodies)
    buf, off = _concat(bodies)
    got = codec.decode_batch_concat(buf, off)
    assert ref is not None and got is not None
    r_ids, r_rat, r_rd, r_thr, r_reg, r_mode, r_st = ref
    g_ids, g_rat, g_rd, g_thr, g_reg, g_mode, g_st = got
    assert (r_st == g_st).all()
    for i in range(N):
        if int(r_st[i]) != codec.OK:
            continue
        assert g_ids[i] == r_ids[i]
        assert g_rat[i] == r_rat[i] and g_rd[i] == r_rd[i]
        assert (math.isnan(g_thr[i]) if math.isnan(r_thr[i])
                else g_thr[i] == r_thr[i])
        assert g_reg[i] == r_reg[i] and g_mode[i] == r_mode[i]
        # Field parity vs the semantic source of truth, directly.
        py = decode_request(bodies[i])
        assert g_ids[i] == py.id
        assert g_rat[i] == pytest.approx(py.rating, rel=1e-6, abs=1e-6)


def test_decode_concat_hostile_offsets_are_bad_json():
    """Inverted, out-of-range, and truncating offsets must come back as
    per-row bad_json — never a read outside the buffer or a crash."""
    bodies = [b'{"id":"a","rating":1}', b'{"id":"b","rating":2}']
    buf, off = _concat(bodies)
    # Truncated final body (offset cut mid-JSON).
    off_trunc = off.copy()
    off_trunc[2] = off[2] - 5
    out = codec.decode_batch_concat(buf, off_trunc)
    assert out is not None
    assert int(out[6][0]) == codec.OK and int(out[6][1]) != codec.OK
    # Inverted span.
    off_inv = off.copy()
    off_inv[1] = off[2]
    off_inv[2] = 0
    out = codec.decode_batch_concat(buf, off_inv)
    assert out is not None and int(out[6][1]) != codec.OK
    # Out-of-range end.
    off_oob = off.copy()
    off_oob[2] = len(buf) + 64
    out = codec.decode_batch_concat(buf, off_oob)
    assert out is not None and int(out[6][1]) != codec.OK
    # Negative start.
    off_neg = off.copy()
    off_neg[0] = -3
    out = codec.decode_batch_concat(buf, off_neg)
    assert out is not None and int(out[6][0]) != codec.OK
    # Empty batch.
    out = codec.decode_batch_concat(b"", np.zeros(1, np.int64))
    assert out is not None and len(out[0]) == 0


def test_decode_concat_needs_python_rows_fall_back():
    """Every NEEDS_PYTHON row of the concat decoder must decode through
    the Python contract (the fallback cannot dead-end), and adjacent rows
    in the arena must not bleed into each other."""
    bodies = [
        json.dumps({"id": 'q"uote', "rating": 1500}).encode(),
        b'{"id":"plain","rating":1400,"region":"eu"}',
        json.dumps({"id": "p", "rating": 1300,
                    "party": [{"id": "m", "rating": 1200}]}).encode(),
    ]
    buf, off = _concat(bodies)
    out = codec.decode_batch_concat(buf, off)
    assert out is not None
    ids, rating, rd, thr, reg, mode, st = out
    assert int(st[0]) == codec.NEEDS_PYTHON
    assert int(st[1]) == codec.OK
    assert int(st[2]) == codec.NEEDS_PYTHON
    assert ids[1] == "plain" and reg[1] == "eu"
    for i in (0, 2):
        py = decode_request(bodies[i])  # fallback must succeed
        assert py.rating > 0


# ---------------------------------------------------------------------------
# encode: matched pairs


def test_fuzz_encode_matched_byte_identical():
    rng = random.Random(99)
    ids_a = [_rand_id(rng) for _ in range(N)]
    ids_b = [_rand_id(rng) for _ in range(N)]
    mids = [f"m{rng.randrange(16**12):012x}" for _ in range(N)]
    lat_a = np.array([_rand_float(rng) for _ in range(N)])
    lat_b = np.array([_rand_float(rng) for _ in range(N)])
    qual = np.array([rng.uniform(0.0, 1.0) for _ in range(N)])
    wa = np.array([_rand_float(rng) for _ in range(N)])
    wb = np.array([_rand_float(rng) for _ in range(N)])
    # Sprinkle non-finite floats: those SIDES must come back None.
    for j in rng.sample(range(N), 20):
        lat_a[j] = rng.choice([float("nan"), float("inf"), -float("inf")])
    tr_a = ["" if rng.random() < 0.5 else f"tr{j}" for j in range(N)]
    bodies = codec.encode_matched_batch(ids_a, ids_b, mids, lat_a, lat_b,
                                        qual, wa, wb, tr_a, None)
    assert bodies is not None and len(bodies) == 2 * N
    n_py = 0
    for j in range(N):
        result = MatchResult(match_id=mids[j], players=(ids_a[j], ids_b[j]),
                             teams=((ids_a[j],), (ids_b[j],)),
                             quality=float(qual[j]))
        for side, (pid, lat, w, tid) in enumerate((
                (ids_a[j], lat_a[j], wa[j], tr_a[j]),
                (ids_b[j], lat_b[j], wb[j], ""))):
            native = bodies[2 * j + side]
            if not math.isfinite(lat):
                # json.dumps would emit non-strict Infinity/NaN — the
                # native encoder refuses rather than approximating.
                if side == 0:
                    assert native is None
                continue
            ascii_pair = all(ord(c) < 128 for c in ids_a[j] + ids_b[j])
            py = encode_response(SearchResponse(
                status="matched", player_id=pid, latency_ms=float(lat),
                waited_ms=float(w), trace_id=tid, match=result))
            if native is None:
                n_py += 1
                assert not ascii_pair or not math.isfinite(
                    lat_a[j] if side else lat)  # a reason must exist
                assert json.loads(py)["player_id"] == pid  # fallback works
                continue
            assert native == py, (pid, lat, w)
    assert n_py < N  # non-ASCII/non-finite rows only


# ---------------------------------------------------------------------------
# encode: queued / timeout / shed


def test_fuzz_encode_simple_byte_identical():
    rng = random.Random(7)
    kinds = [rng.randrange(3) for _ in range(N)]
    pids = [_rand_id(rng) for _ in range(N)]
    lat = np.array([_rand_float(rng) for _ in range(N)])
    retry = np.array([abs(_rand_float(rng)) for _ in range(N)])
    traces = ["" if rng.random() < 0.5 else f"t{j}" for j in range(N)]
    tiers = np.array([-1 if rng.random() < 0.5 else rng.randrange(4)
                      for _ in range(N)], np.int32)
    bodies = codec.encode_simple_batch(kinds, pids, lat, retry, traces,
                                       tiers)
    assert bodies is not None
    statuses = {codec.KIND_QUEUED: "queued", codec.KIND_TIMEOUT: "timeout",
                codec.KIND_SHED: "shed"}
    n_py = 0
    for j in range(N):
        py = encode_response(SearchResponse(
            status=statuses[kinds[j]], player_id=pids[j],
            latency_ms=float(lat[j]), retry_after_ms=float(retry[j]),
            trace_id=traces[j],
            tier=None if tiers[j] < 0 else int(tiers[j])))
        if bodies[j] is None:
            n_py += 1
            assert any(ord(c) >= 128 for c in pids[j]), pids[j]
            assert json.loads(py)["status"] == statuses[kinds[j]]
            continue
        assert bodies[j] == py, (kinds[j], pids[j], lat[j])
    assert n_py < N // 4


def test_rebuild_from_source(tmp_path):
    """codec.rebuild(): the CI seam — the library must (re)build from
    codec.cc on demand and come back available (check.sh calls this so
    the parity gate never tests a stale checked-in .so)."""
    assert codec.rebuild() is True
    assert codec.available()
