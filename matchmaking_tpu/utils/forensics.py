"""Incident forensics (ISSUE 18): causal event spine + black-box capture.

PRs 15-17 made failures survivable; this module makes them debuggable.
Three pieces, all bounded-memory and stdlib-only:

- ``EventSpine`` — ONE process-wide monotone sequence stamped onto every
  lifecycle emission (EventLog appends, knob decisions, placement audit
  records, replication role/epoch transitions, journal compaction/replay,
  breaker transitions, SLO burns, speculation invalidations). Each event
  carries ``(seq, mono_ns, wall, component, queue, kind, detail, refs)``
  where ``refs`` links causal neighbors (epoch, WAL seq range, decision
  id, player counts) — a single ordered timeline spanning
  engine → service → control → replication, instead of five private
  rings with no shared ordering. The seq is an ``itertools.count`` under
  a lock (appends come from the event loop AND engine worker threads);
  ``mono_ns`` is ``time.monotonic_ns()`` so two events in the same wall
  millisecond still order causally, and ``wall`` stays plain data for
  humans. The DETERMINISTIC subset of the spine (scripted-recovery kinds
  + counter-valued refs, no clocks) is the ``transcript()`` — the
  bit-identical-across-two-runs artifact ``bench.py --incident-soak``
  pins, the same determinism bar the crash/failover soaks meet.
- ``IncidentRecorder`` — the black box. A trigger-rule table over spine
  kinds (SLO burn start, breaker trip, failover takeover, crash
  recovery, migration blackout over budget, autotuner oscillation)
  freezes the relevant rings — spine window, telemetry tail, slow-trace
  exemplars, attribution snapshot, placement/autotune audit slices,
  replication watermarks, journal watermark digest — into a bounded,
  schema-versioned JSON bundle (``mm.incident/1``), kept in an in-proc
  ring (``/debug/incidents``) and optionally written under a
  configurable directory with a retention cap. Captures are rate-limited
  per trigger class (a burn storm must not self-amplify: dropped
  captures are COUNTED, never silent) and measured (capture-duration
  series → the p99 the incident-soak gates at <= 50 ms). Capture is
  read-only against the same thread-safe snapshot surfaces /metrics
  already scrapes, so it can fire mid-drain without blocking the drain
  or touching a settlement credit.
- ``validate_bundle`` — the schema checker ``check.sh`` runs over every
  committed example bundle and the analyzer runs before rendering.

The offline analyzer lives in ``scripts/postmortem.py``; the live
rendering in ``scripts/trace_dump.py --incident``.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable

#: Bundle schema version: bump on any breaking field change; the
#: validator and the offline analyzer both check it.
INCIDENT_SCHEMA = "mm.incident/1"

#: Spine kind → component. Every emitter routes through EventLog.append,
#: which resolves the component here when the call site doesn't say —
#: the table keeps ~40 existing call sites untouched while the timeline
#: still answers "which layer said that".
_COMPONENT_PREFIXES = (
    ("autotune", "control"),
    ("placement", "control"),
    ("migrate", "control"),
    ("replication", "replication"),
    ("failover", "replication"),
    ("lease", "replication"),
    ("epoch", "replication"),
    ("replay", "replication"),
    ("journal", "durability"),
    ("crash", "durability"),
    ("checkpoint", "durability"),
    ("backlog", "durability"),
    ("slo_", "slo"),
    ("chaos", "chaos"),
    ("spec_", "engine"),
    ("team_", "engine"),
    ("engine", "engine"),
    ("window_failed", "engine"),
    ("rescan", "engine"),
    ("breaker", "service"),
    ("probe", "service"),
    ("drain", "service"),
    ("shed", "service"),
    ("expired", "service"),
    ("partition", "broker"),
    ("dead_letter", "broker"),
)


def component_of(kind: str) -> str:
    for prefix, component in _COMPONENT_PREFIXES:
        if kind.startswith(prefix):
            return component
    return "service"


#: Spine kinds whose emission is a pure function of the scripted load
#: (recovery/takeover chains, counter-valued refs) — the deterministic
#: transcript the incident-soak compares bit-identically across runs.
#: Burn/breaker/chaos kinds are wall-clock-shaped and stay out.
DETERMINISTIC_KINDS = (
    "lease_expired", "epoch_bump", "replay_window", "failover_takeover",
    "crash_recovered", "replication_attached",
)

#: Refs keys that are counters/identities (deterministic under a seeded
#: designed load); timing-valued refs (rto_ms, blackout_ms, burn rates)
#: are excluded from the transcript by this allowlist.
_TRANSCRIPT_REF_KEYS = ("epoch", "prev_epoch", "players", "records",
                        "snapshot_players", "decision", "knob")


class EventSpine:
    """Process-wide causal ordering for lifecycle events. One instance
    per app (not a module global): two seeded runs must each start their
    sequence at 1 or the transcript identity pin is meaningless."""

    def __init__(self, ring: int = 4096):
        # guarded-by: _lock
        self._ring: deque[dict[str, Any]] = deque(maxlen=max(16, ring))
        self._seq = itertools.count(1)
        #: Guards seq draw + ring append as one step so ring order IS seq
        #: order even under concurrent worker-thread emitters.
        self._lock = threading.Lock()
        self._observers: list[Callable[[dict[str, Any]], None]] = []

    def subscribe(self, fn: Callable[[dict[str, Any]], None]) -> None:
        self._observers.append(fn)

    def stamp(self, kind: str, queue: str = "", detail: str = "",
              component: str = "", refs: "dict[str, Any] | None" = None,
              wall: "float | None" = None) -> dict[str, Any]:
        """Stamp one event onto the spine and return the stamped row.
        Observers run OUTSIDE the lock: a capture triggered by this very
        event must not block other threads' emissions (or a drain) for
        the capture's duration."""
        ev = {
            "seq": 0,  # assigned under the lock below
            "mono_ns": time.monotonic_ns(),
            "wall": time.time() if wall is None else wall,
            "component": component or component_of(kind),
            "queue": queue,
            "kind": kind,
            "detail": detail,
            "refs": dict(refs) if refs else {},
        }
        with self._lock:
            ev["seq"] = next(self._seq)
            self._ring.append(ev)
        for fn in tuple(self._observers):
            try:
                fn(ev)
            except Exception:
                # A broken observer (capture bug) must never take the
                # emitting subsystem down with it.
                pass
        return ev

    def __len__(self) -> int:
        return len(self._ring)

    def window(self, limit: int = 0, queue: "str | None" = None,
               kinds: "Iterable[str] | None" = None) -> list[dict[str, Any]]:
        """Seq-ordered slice of the ring (newest ``limit`` rows). tuple()
        first: worker threads append concurrently and iterating a live
        deque across their mutations raises RuntimeError."""
        want = set(kinds) if kinds is not None else None
        rows = [dict(ev) for ev in tuple(self._ring)
                if (queue is None or ev["queue"] == queue)
                and (want is None or ev["kind"] in want)]
        rows.sort(key=lambda ev: ev["seq"])
        return rows[-limit:] if limit else rows

    def transcript(self, kinds: "Iterable[str] | None" = None,
                   ) -> list[dict[str, Any]]:
        """The deterministic projection: seq-ORDERED rows of
        (component, queue, kind, allowlisted refs) with every clock field
        dropped — what two seeded runs must reproduce bit-identically."""
        rows = []
        for ev in self.window(kinds=kinds or DETERMINISTIC_KINDS):
            refs = {k: ev["refs"][k] for k in _TRANSCRIPT_REF_KEYS
                    if k in ev["refs"]}
            rows.append({"component": ev["component"], "queue": ev["queue"],
                         "kind": ev["kind"], "refs": refs})
        return rows

    def digest(self, kinds: "Iterable[str] | None" = None) -> str:
        """sha256 over the deterministic transcript — the one-line
        identity pin bundles and the incident-soak carry."""
        blob = json.dumps(self.transcript(kinds), sort_keys=True,
                          separators=(",", ":")).encode("utf-8")
        return hashlib.sha256(blob).hexdigest()


# ---- black-box auto-capture -------------------------------------------------

#: Trigger rules: spine kind → trigger class. One bundle per firing
#: (subject to the per-class rate limit). ``slo_burn_clear`` is a
#: checkpoint trigger on purpose: the takeover root-chain terminates at
#: the burn CLEARING, so the post-recovery bundle must exist too.
TRIGGER_KINDS = {
    "slo_burn": "slo_burn",
    "slo_burn_clear": "slo_burn_clear",
    "breaker_trip": "breaker_trip",
    "failover_takeover": "failover",
    "crash_recovered": "crash_recovery",
    "placement_blackout_over_budget": "blackout_over_budget",
    "autotune_oscillation": "autotune_oscillation",
}

#: Required top-level bundle fields (the schema the validator + check.sh
#: enforce over committed examples).
_BUNDLE_REQUIRED = ("schema", "id", "trigger", "captured_wall",
                    "capture_ms", "spine", "spine_digest", "telemetry",
                    "replication", "journal", "counters")
_TRIGGER_REQUIRED = ("class", "seq", "kind", "queue", "detail", "refs")


class IncidentRecorder:
    """Subscribes to the app's EventSpine; freezes bounded ring snapshots
    into schema-versioned incident bundles when a trigger rule fires."""

    def __init__(self, app, cfg):
        self.app = app
        self.cfg = cfg
        # guarded-by: _lock
        self._ring: deque[dict[str, Any]] = deque(
            maxlen=max(1, cfg.incident_ring))
        self._lock = threading.Lock()
        self._id = itertools.count(1)
        #: Per-trigger-class monotonic stamp of the last capture (the
        #: rate limiter's memory) and the last few autotune moves per
        #: (queue, knob) for the oscillation detector.
        self._last_capture: dict[str, float] = {}  # guarded-by: _lock
        # guarded-by: _lock
        self._moves: dict[tuple[str, str], deque[tuple[Any, Any]]] = {}
        self._capturing = False  # guarded-by: _lock
        self.captured = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock
        self.by_class: dict[str, int] = {}  # guarded-by: _lock
        if cfg.enabled():
            app.spine.subscribe(self.observe)

    # -- trigger matching ---------------------------------------------------

    def observe(self, ev: dict[str, Any]) -> None:
        """Spine observer (runs outside the spine lock, possibly on an
        engine worker thread). Cheap non-match path: one dict lookup."""
        kind = ev["kind"]
        if kind.startswith("autotune_") and kind not in TRIGGER_KINDS:
            self._observe_knob_move(ev)
            return
        cls = TRIGGER_KINDS.get(kind)
        if cls is None:
            return
        if cls == "blackout_over_budget" and self.cfg.blackout_budget_ms <= 0:
            return
        self._fire(cls, ev)

    def _observe_knob_move(self, ev: dict[str, Any]) -> None:
        """Autotuner oscillation: the same knob on the same queue flips
        src→dst then dst→src within the configured move window — the
        tuner is chasing its own tail and an operator needs the signal
        slice that confused it."""
        refs = ev.get("refs") or {}
        src, dst = refs.get("src"), refs.get("dst")
        if src is None or dst is None:
            return
        key = (ev["queue"], ev["kind"])
        # Observers run on whatever thread emitted the spine event, so
        # the move rings mutate under the lock — but the oscillation
        # emission below stays OUTSIDE it: events.append re-enters
        # observe → _fire, which takes this same (non-reentrant) lock.
        with self._lock:
            ring = self._moves.get(key)
            if ring is None:
                ring = self._moves[key] = deque(
                    maxlen=max(2, self.cfg.oscillation_window))
            flip = any(p_src == dst and p_dst == src
                       for p_src, p_dst in ring)
            ring.append((src, dst))
        if flip:
            osc = self.app.events.append(
                "autotune_oscillation", ev["queue"],
                f"{ev['kind']} flip {dst} -> {src} -> {dst} within "
                f"{ring.maxlen} moves", component="control",
                refs={"knob": refs.get("knob", ev["kind"]),
                      "decision": refs.get("decision")})
            # append() already re-entered observe() with the oscillation
            # event, which fired the trigger — nothing more to do here.
            del osc

    def _fire(self, cls: str, ev: dict[str, Any]) -> None:
        now = time.monotonic()
        with self._lock:
            if self._capturing:
                # A capture in flight triggered a spine event that itself
                # matches a rule — self-amplification, by definition.
                self.dropped += 1
                self.app.metrics.counters.inc("incidents_dropped")
                return
            last = self._last_capture.get(cls)
            if (last is not None
                    and now - last < self.cfg.min_interval_s):
                self.dropped += 1
                self.app.metrics.counters.inc("incidents_dropped")
                return
            self._last_capture[cls] = now
            self._capturing = True
        try:
            self.capture(cls, ev)
        finally:
            with self._lock:
                self._capturing = False

    # -- bundle assembly ----------------------------------------------------

    def capture(self, cls: str, ev: dict[str, Any]) -> dict[str, Any]:
        """Freeze the rings into one bundle. Read-only against the same
        thread-safe snapshot surfaces /metrics scrapes; measured into the
        ``incident_capture`` latency series (the p99 the soak gates)."""
        t0 = time.perf_counter()
        app = self.app
        cfg = self.cfg
        bundle: dict[str, Any] = {
            "schema": INCIDENT_SCHEMA,
            "id": f"inc-{next(self._id):06d}",
            "trigger": {"class": cls, "seq": ev["seq"], "kind": ev["kind"],
                        "queue": ev["queue"], "detail": ev["detail"],
                        "refs": dict(ev["refs"]),
                        "mono_ns": ev["mono_ns"], "wall": ev["wall"]},
            "captured_wall": time.time(),
            "capture_ms": 0.0,  # patched below, after the freeze
            "spine": app.spine.window(limit=cfg.spine_window),
            "spine_digest": app.spine.digest(),
            "telemetry": app.telemetry.snapshot(limit=cfg.telemetry_tail),
            "counters": {},
            "replication": {},
            "journal": {},
        }
        counters = app.metrics.report()["counters"]
        bundle["counters"] = {k: v for k, v in sorted(counters.items())
                              if v}
        recorder = getattr(app, "recorder", None)
        if recorder is not None and getattr(app, "trace_enabled", True):
            snap = recorder.snapshot(limit=cfg.trace_slice)
            # Slow exemplars only: the recent ring is volume, the slow
            # ring is the incident's latency evidence.
            bundle["slow_traces"] = {
                q: entry["slow"] for q, entry in snap["queues"].items()
                if entry["slow"]}
        attribution = getattr(app, "attribution", None)
        if attribution is not None:
            bundle["attribution"] = attribution.snapshot()
        slo = {name: mon.snapshot()
               for name, mon in getattr(app, "_slo_monitors", {}).items()}
        if slo:
            bundle["slo"] = slo
        placement = getattr(app, "placement", None)
        if placement is not None:
            bundle["placement"] = placement.snapshot(
                history=cfg.audit_slice)
        tuner = getattr(app, "autotune", None)
        if tuner is not None:
            bundle["autotune"] = tuner.snapshot(history=cfg.audit_slice)
        for name, rt in app._runtimes.items():
            repl = getattr(rt, "replication", None)
            if repl is not None:
                bundle["replication"][name] = repl.snapshot()
            j = getattr(rt, "journal", None)
            if j is not None:
                watermark = {"seq": j.seq, "synced_seq": j.synced_seq,
                             "segment_records": j.segment_records,
                             "segment_bytes": j.segment_bytes,
                             "path": getattr(j, "path", "")}
                # The tail digest names exactly which WAL window the
                # bundle saw — journal_dump.py --lsn-range slices it.
                blob = json.dumps(
                    {k: watermark[k] for k in
                     ("seq", "synced_seq", "segment_records")},
                    sort_keys=True).encode("utf-8")
                watermark["lsn_range"] = [
                    max(0, j.seq - j.segment_records), j.seq]
                watermark["tail_digest"] = hashlib.sha256(blob).hexdigest()
                bundle["journal"][name] = watermark
        capture_ms = (time.perf_counter() - t0) * 1e3
        bundle["capture_ms"] = round(capture_ms, 3)
        app.metrics.record_latency("incident_capture", capture_ms / 1e3)
        app.metrics.counters.inc("incidents_captured")
        with self._lock:
            self.captured += 1
            self.by_class[cls] = self.by_class.get(cls, 0) + 1
            self._ring.append(bundle)
        if cfg.incident_dir:
            self._persist(bundle)
        return bundle

    def _persist(self, bundle: dict[str, Any]) -> None:
        """Write one bundle file; prune oldest past the retention cap.
        Best-effort: a full disk must not take the service down."""
        import os

        try:
            os.makedirs(self.cfg.incident_dir, exist_ok=True)
            path = os.path.join(
                self.cfg.incident_dir,
                f"incident_{bundle['id']}_{bundle['trigger']['class']}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, sort_keys=True)
            os.replace(tmp, path)
            kept = sorted(
                f for f in os.listdir(self.cfg.incident_dir)
                if f.startswith("incident_") and f.endswith(".json"))
            for stale in kept[:-max(1, self.cfg.retention_files)]:
                try:
                    os.unlink(os.path.join(self.cfg.incident_dir, stale))
                except OSError:
                    pass
        except OSError:
            self.app.metrics.counters.inc("incidents_persist_errors")

    # -- debug surfaces -----------------------------------------------------

    def get(self, incident_id: str) -> "dict[str, Any] | None":
        with self._lock:
            for bundle in self._ring:
                if bundle["id"] == incident_id:
                    return bundle
        return None

    def snapshot(self, include_bundles: bool = False) -> dict[str, Any]:
        """Counters + bundle summaries for /debug/incidents, /metrics and
        /healthz. Summaries stay small; the full bundle is per-id fetch."""
        lat = self.app.metrics.latency.get("incident_capture")
        with self._lock:
            bundles = list(self._ring)
            body: dict[str, Any] = {
                "captured": self.captured,
                "dropped": self.dropped,
                "by_class": dict(sorted(self.by_class.items())),
                "incident_dir": self.cfg.incident_dir,
                "capture_ms_p99": (
                    round(lat.percentile(99) * 1e3, 3)
                    if lat is not None and len(lat) else None),
            }
        body["incidents"] = [
            {"id": b["id"], "class": b["trigger"]["class"],
             "kind": b["trigger"]["kind"], "queue": b["trigger"]["queue"],
             "seq": b["trigger"]["seq"], "wall": b["trigger"]["wall"],
             "capture_ms": b["capture_ms"],
             "spine_events": len(b["spine"])}
            for b in bundles]
        if include_bundles:
            body["bundles"] = bundles
        return body


def validate_bundle(bundle: Any) -> list[str]:
    """Schema check (``check.sh`` runs this over every committed example;
    the analyzer runs it before rendering). Returns human-readable
    problems, [] when the bundle is valid."""
    problems: list[str] = []
    if not isinstance(bundle, dict):
        return [f"bundle must be a JSON object, got {type(bundle).__name__}"]
    if bundle.get("schema") != INCIDENT_SCHEMA:
        problems.append(
            f"schema {bundle.get('schema')!r} != {INCIDENT_SCHEMA!r}")
    for field in _BUNDLE_REQUIRED:
        if field not in bundle:
            problems.append(f"missing required field {field!r}")
    trigger = bundle.get("trigger")
    if isinstance(trigger, dict):
        for field in _TRIGGER_REQUIRED:
            if field not in trigger:
                problems.append(f"trigger missing field {field!r}")
        if trigger.get("class") not in set(TRIGGER_KINDS.values()):
            problems.append(
                f"unknown trigger class {trigger.get('class')!r}")
    elif "trigger" in bundle:
        problems.append("trigger must be an object")
    spine = bundle.get("spine")
    if isinstance(spine, list):
        prev = 0
        for i, ev in enumerate(spine):
            if not isinstance(ev, dict):
                problems.append(f"spine[{i}] is not an object")
                break
            missing = [k for k in ("seq", "mono_ns", "wall", "component",
                                   "queue", "kind", "refs")
                       if k not in ev]
            if missing:
                problems.append(f"spine[{i}] missing {missing}")
                break
            if ev["seq"] <= prev:
                problems.append(
                    f"spine[{i}] seq {ev['seq']} not strictly increasing "
                    f"(prev {prev}) — causal order broken")
                break
            prev = ev["seq"]
    elif "spine" in bundle:
        problems.append("spine must be a list")
    if "capture_ms" in bundle and not isinstance(
            bundle["capture_ms"], (int, float)):
        problems.append("capture_ms must be a number")
    return problems
