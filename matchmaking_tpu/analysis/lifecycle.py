"""``settlement`` + ``lock-pairing``: flow-sensitive lifecycle typestates.

**settlement** — the exactly-once settlement contract on the request
lifecycle (service/app.py): every ``Delivery`` that takes an admission
credit must reach exactly one settlement — ``_ack`` / ``_nack`` / shed /
expired / batch settle — on EVERY path, including the exception edges the
PR 5 comments could only warn about ("a leaked credit would tighten
admission forever").  Built on the dataflow CFG, so an exception raised
between ``admission.admit`` and the release handler IS a reported path,
and a second ``_ack`` reached through a helper call is a double-settle.

Per-variable abstract states::

    pend     bound, no obligation yet (no credit; broker-level requeue
             recovers a crash, so unsettled exception exits are fine)
    held     admission credit taken (``admission.admit(..tag..)``) —
             MUST settle before any exit, INCLUDING exception edges
    settled  reached a settlement
    escaped  ownership transferred (batcher submit, stored into window
             meta, appended into a caller-owned container, returned)
    handled  settled on some paths, escaped on others (fine)
    mix      settled/escaped on some paths, still pending on others —
             conditionally settled (reported at joins that leave the
             variable's scope: loop-back rebinds and function exits)

Annotation vocabulary (comment on or above a ``def`` / assignment):

- ``# settles: delivery`` — calling this function settles the named
  parameter exactly once (the call site transition; inside the function
  the normal-exit contract is checked).  On the call's EXCEPTION edge the
  argument stays unsettled — the callee only promises settlement when it
  returns (so ``_flush``'s except-handler nack after a half-settled
  ``_flush_inner`` is NOT a double-settle).
- ``# settles: *deliveries`` — collection form: the function settles
  every element of the named iterable before a normal return.
- ``# settles-some: pairs`` — partial contract: the function settles an
  input-dependent subset (dedup replays, debt victims).  Documents the
  seam and suppresses conditional-settlement reports for the parameter
  inside the function; call sites get no transition (the caller still
  owns the rest).
- ``# owns: deliveries`` — on an assignment: arms a LOCAL collection
  (e.g. window meta popped back out of ``_inflight_meta``) with the same
  settle-before-return obligation as ``settles: *``.

Raw settlement primitives — ``*.broker.ack/nack(.., var.delivery_tag ..)``
and ``*.admission.release(var.delivery_tag)`` — settle without the
double-settle check (release is idempotent BY DESIGN: every settle path
calls it blindly), and ``*.admission.admit(var.delivery_tag ..)`` is the
credit acquire that arms the ``held`` obligation.

Collections: aliases are grouped syntactically (filter comprehensions,
``sorted(...)``, appends of loop elements), a ``for`` loop whose target is
settled on every path settles the collection, and the ``if not window:
return`` emptiness shape is recognized as a vacuous settle on the true
branch.

**lock-pairing** — the same acquire/release machinery generalized to
explicit lock calls: within a function using ``X.acquire()`` /
``X.release()`` on a lock-named object, every path must balance —
acquire-while-held, release-while-free, and any exit (including exception
edges) while holding are reported.  ``with``-statement locks never hit
this rule (the context manager balances by construction); it exists for
the hand-rolled pairings a future migration/retry path would add.
"""

from __future__ import annotations

import ast
import copy
import re
from typing import Any

from matchmaking_tpu.analysis import dataflow as df
from matchmaking_tpu.analysis.core import (
    Finding,
    SourceFile,
    dotted_name,
    in_package,
)

RULE = "settlement"
LOCK_RULE = "lock-pairing"

_SETTLES_RE = re.compile(r"#\s*settles:\s*([\w\s,*]+)")
_SETTLES_SOME_RE = re.compile(r"#\s*settles-some:\s*(\w+)")
_OWNS_RE = re.compile(r"#\s*owns:\s*(\w+)")

#: Mutating container methods that transfer an element to the receiver.
_ESCAPE_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "put",
    "put_nowait", "submit",
})

#: (receiver-leaf, method) pairs for the raw settle/acquire primitives.
_RAW_SETTLE = {("broker", "ack"), ("broker", "nack"),
               ("admission", "release")}
_RAW_ACQUIRE = {("admission", "admit")}

# Abstract states.
PEND, HELD, SETTLED, ESCAPED, HANDLED, MIX = (
    "pend", "held", "settled", "escaped", "handled", "mix")
_OK_EXIT = {SETTLED, ESCAPED, HANDLED}


def _comment_above(sf: SourceFile, lineno: int, rx: re.Pattern):
    """Match on the line itself or a contiguous comment block above it
    (settlement annotations stack with holds-lock / guarded-by ones)."""
    m = rx.search(sf.line_at(lineno))
    if m:
        return m
    ln = lineno - 1
    while ln > 0 and sf.line_at(ln).strip().startswith("#"):
        m = rx.search(sf.line_at(ln))
        if m:
            return m
        ln -= 1
    return None


class _FnContract:
    """One function's settlement annotations."""

    __slots__ = ("node", "settles", "settles_coll", "settles_some")

    def __init__(self, node: ast.AST):
        self.node = node
        self.settles: dict[str, int] = {}       # param -> position
        self.settles_coll: dict[str, int] = {}  # collection param -> pos
        self.settles_some: set[str] = set()


def _collect_contracts(sf: SourceFile) -> dict[str, _FnContract]:
    """qualname (Class.method or function) → contract, same-file only (the
    settlement seams all live inside service/app.py by design)."""
    out: dict[str, _FnContract] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for item in ast.iter_child_nodes(node):
            if isinstance(item, ast.ClassDef):
                visit(item, item.name + ".")
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                c = _FnContract(item)
                params = [a.arg for a in (*item.args.posonlyargs,
                                          *item.args.args)]
                m = _comment_above(sf, item.lineno, _SETTLES_RE)
                if m:
                    for raw in m.group(1).split(","):
                        raw = raw.strip()
                        if not raw:
                            continue
                        coll = raw.startswith("*")
                        name = raw.lstrip("*").strip()
                        if name in params:
                            pos = params.index(name)
                            (c.settles_coll if coll
                             else c.settles)[name] = pos
                m = _comment_above(sf, item.lineno, _SETTLES_SOME_RE)
                if m and m.group(1) in params:
                    c.settles_some.add(m.group(1))
                out[prefix + item.name] = c
                visit(item, prefix)  # nested defs keep the outer prefix
    visit(sf.tree, "")
    return out


def _leaf_pair(call: ast.Call) -> tuple[str, str] | None:
    """Last two components of a dotted callee (``self.app.broker.ack`` →
    ``("broker", "ack")``)."""
    name = dotted_name(call.func)
    parts = name.split(".") if name else []
    if len(parts) >= 2:
        return parts[-2], parts[-1]
    return None


def _callee_name(call: ast.Call) -> str:
    """Leaf method/function name of the callee ('' when not a chain)."""
    name = dotted_name(call.func)
    return name.rsplit(".", 1)[-1] if name else ""


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _bare_names(node: ast.AST) -> set[str]:
    """Names mentioned as VALUES (the object itself or an element of it),
    not as the base of a field read: ``(req, delivery)`` hands
    ``delivery`` off and ``x[k] = deliveries[s]`` hands an element off,
    while ``delivery.tier`` / ``deliveries[s].tier`` only read a field
    and transfer nothing."""
    shielded: set[int] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            base = sub.value
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            if isinstance(base, ast.Name):
                shielded.add(id(base))
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and id(n) not in shielded}


def _alias_sources(value: ast.AST) -> set[str]:
    """Collection names a Name-assignment RHS aliases: a bare name, a
    ``sorted``/``list``/``tuple``/``reversed`` of one, or a comprehension
    whose iteration source (or subscripted element, the ``deliveries[s]``
    view shape) is one.  Deliberately narrow — arbitrary expressions do
    NOT join the alias group, or every scratch local would."""
    if isinstance(value, ast.Name):
        return {value.id}
    if (isinstance(value, ast.Call)
            and _callee_name(value) in ("sorted", "list", "tuple",
                                        "reversed")
            and value.args and isinstance(value.args[0], ast.Name)):
        return {value.args[0].id}
    if isinstance(value, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
        out: set[str] = set()
        for gen in value.generators:
            if isinstance(gen.iter, ast.Name):
                out.add(gen.iter.id)
        for sub in ast.walk(value.elt):
            if (isinstance(sub, ast.Subscript)
                    and isinstance(sub.value, ast.Name)):
                out.add(sub.value.id)
        return out
    return set()


def _filter_predicates(value: ast.AST) -> set[str]:
    """Normalized (``ast.dump``) filter predicates applied by
    comprehensions inside an assignment RHS — the inputs to the
    collection-length value-flow refinement (ISSUE 13 satellite). Two
    shapes produce a predicate:

    - a generator ``if`` condition (``[d for ... in ... if pid not in
      drop]``), and
    - the boolean MASK-VECTOR element (no ``if``s, a bare Compare/BoolOp
      element — the ``np.fromiter((pid not in drop for pid in ...),
      bool, n)`` idiom whose result feeds ``.take(mask)``).

    Predicates that reference no name beyond the comprehension's own
    targets are dropped: an unanchored filter (``if x`` over the loop
    variable alone) identifies nothing across assignments."""
    out: set[str] = set()
    for sub in ast.walk(value):
        if not isinstance(sub, (ast.ListComp, ast.GeneratorExp,
                                ast.SetComp)):
            continue
        bound: set[str] = set()
        conds: list[ast.AST] = []
        for gen in sub.generators:
            bound |= set(_binding_names(gen.target))
            conds.extend(gen.ifs)
        if not conds and isinstance(sub.elt, (ast.Compare, ast.BoolOp)):
            conds = [sub.elt]
        for cond in conds:
            if _names_in(cond) - bound:
                out.add(ast.dump(cond))
    return out


def _binding_names(target: ast.AST) -> list[str]:
    """Plain Name targets bound by an assignment/loop target."""
    out = []
    for t in ast.walk(target):
        if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store):
            out.append(t.id)
    return out


def _calls_in_header(stmt: ast.AST) -> list[ast.Call]:
    calls: list[ast.Call] = []
    for expr in df.header_exprs(stmt):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                calls.append(sub)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                break  # opaque nested scope
    return calls


def _mentions_tag(call: ast.Call, var: str) -> bool:
    """Does any argument read ``var.delivery_tag``?"""
    for arg in (*call.args, *(kw.value for kw in call.keywords)):
        for sub in ast.walk(arg):
            if (isinstance(sub, ast.Attribute)
                    and sub.attr == "delivery_tag"
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == var):
                return True
    return False


class _Groups:
    """Union-find over collection-variable names (one function)."""

    def __init__(self) -> None:
        self._parent: dict[str, str] = {}

    def find(self, name: str) -> str:
        p = self._parent.setdefault(name, name)
        if p != name:
            p = self._parent[name] = self.find(p)
        return p

    def union(self, a: str, b: str) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self._parent[ra] = rb


class _FnScan:
    """Syntactic pre-pass over one function: alias groups, tracked vars,
    loop metadata."""

    def __init__(self, fn: ast.AST, contract: _FnContract,
                 contracts: dict[str, _FnContract], cls: str):
        self.fn = fn
        self.contract = contract
        self.contracts = contracts
        self.cls = cls
        self.groups = _Groups()
        #: Names armed as owned collections (annotated params + # owns:
        #: locals), by group root after unioning.
        self.owned_seeds: set[str] = set(contract.settles_coll)
        self.partial_seeds: set[str] = set(contract.settles_some)
        self.tracked: set[str] = set()       # scalar vars under analysis
        self.partial_loops: set[int] = set() # For linenos that keep rows
        self._loop_src: dict[str, str] = {}  # loop target -> iterated name
        #: Guard flags (path-sensitive refinement, ISSUE 11 satellite):
        #: flag name -> the group ROOT whose hand-off it mirrors.  A
        #: qualifying flag is a bool local whose ONLY ``flag = True``
        #: assignment is the statement IMMEDIATELY after a hand-off of an
        #: owned collection (subscript/attribute store), with at least one
        #: ``flag = False`` elsewhere and no other assignments — so
        #: ``flag`` being truthy IMPLIES the group escaped, on every path.
        self.guard_flags: dict[str, str] = {}
        self._scan()

    # A call's contract, resolved same-file: self.helper → Class.helper,
    # bare helper → module function.
    def resolve(self, call: ast.Call) -> _FnContract | None:
        name = dotted_name(call.func)
        if not name:
            return None
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2 and self.cls:
            return self.contracts.get(f"{self.cls}.{parts[1]}")
        if len(parts) == 1:
            return self.contracts.get(parts[0])
        return None

    def _arg_exprs(self, call: ast.Call,
                   contract: _FnContract) -> dict[int, ast.AST]:
        """Position → argument expression, with kwargs mapped through the
        callee's parameter names (self-calls shift positions by one)."""
        params = [a.arg for a in (*contract.node.args.posonlyargs,
                                  *contract.node.args.args)]
        shift = 1 if (params and params[0] == "self"
                      and isinstance(call.func, ast.Attribute)) else 0
        out: dict[int, ast.AST] = {}
        for i, arg in enumerate(call.args):
            out[i + shift] = arg
        for kw in call.keywords:
            if kw.arg and kw.arg in params:
                out[params.index(kw.arg)] = kw.value
        return out

    @staticmethod
    def _loop_source(node: "ast.For | ast.AsyncFor") -> str | None:
        it = node.iter
        if isinstance(it, ast.Name):
            return it.id
        if (isinstance(it, ast.Call)
                and _callee_name(it) in ("enumerate", "sorted", "reversed",
                                         "list", "zip")
                and it.args and isinstance(it.args[0], ast.Name)):
            return it.args[0].id
        return None

    def _scan(self) -> None:
        fn = self.fn
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                src = self._loop_source(node)
                if src is None:
                    continue
                targets = set(_binding_names(node.target))
                for t in targets:
                    self._loop_src[t] = src
                # Loop-LOCAL element hand-off: appending an expression
                # mentioning this loop's own target joins the container to
                # the iterated collection's alias group.  Must be scoped to
                # this loop — a later loop may rebind the same target name
                # from a different source.
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Call)
                            and _callee_name(sub) in _ESCAPE_METHODS
                            and isinstance(sub.func, ast.Attribute)
                            and isinstance(sub.func.value, ast.Name)):
                        container = sub.func.value.id
                        if any(n in targets for arg in sub.args
                               for n in _names_in(arg)):
                            self.groups.union(container, src)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    for n in _alias_sources(node.value):
                        if n != tgt.id:
                            self.groups.union(tgt.id, n)
            elif isinstance(node, ast.Call):
                # Settle / acquire / raw events arm scalar tracking.
                contract = self.resolve(node)
                if contract is not None and (contract.settles
                                             or contract.settles_coll):
                    args = self._arg_exprs(node, contract)
                    for pos in contract.settles.values():
                        if pos in args:
                            self.tracked.update(_names_in(args[pos]))
                pair = _leaf_pair(node)
                if pair in _RAW_SETTLE or pair in _RAW_ACQUIRE:
                    for arg in (*node.args,
                                *(kw.value for kw in node.keywords)):
                        for sub in ast.walk(arg):
                            if (isinstance(sub, ast.Attribute)
                                    and sub.attr == "delivery_tag"
                                    and isinstance(sub.value, ast.Name)):
                                self.tracked.add(sub.value.id)
        # Loop targets over owned groups are tracked (the collection-settle
        # check reads their state at loop exhaustion).
        owned_roots = {self.groups.find(n) for n in self.owned_seeds}
        for t, src in self._loop_src.items():
            if self.groups.find(src) in owned_roots:
                self.tracked.add(t)
        # Partial loops: the body re-appends the loop element into the SAME
        # group it iterates (dedup keeps, debt survivors) — such a loop can
        # never fully settle its collection.
        for node in ast.walk(fn):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            it_names = _names_in(node.iter)
            roots = {self.groups.find(n) for n in it_names}
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and _callee_name(sub) in _ESCAPE_METHODS
                        and isinstance(sub.func, ast.Attribute)
                        and isinstance(sub.func.value, ast.Name)
                        and self.groups.find(sub.func.value.id) in roots):
                    self.partial_loops.add(node.lineno)
        # Guard flags: scan statement SEQUENCES for the hand-off/flag
        # adjacency, then validate the flag's full assignment set.
        bool_assigns: dict[str, list[ast.Assign]] = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, bool)):
                bool_assigns.setdefault(node.targets[0].id,
                                        []).append(node)
        candidates: dict[str, tuple[str, ast.Assign]] = {}
        for node in ast.walk(fn):
            for field in ("body", "orelse", "finalbody"):
                body = getattr(node, field, None)
                if not isinstance(body, list):
                    continue
                for prev, cur in zip(body, body[1:]):
                    if not (isinstance(cur, ast.Assign)
                            and len(cur.targets) == 1
                            and isinstance(cur.targets[0], ast.Name)
                            and isinstance(cur.value, ast.Constant)
                            and cur.value.value is True):
                        continue
                    root = self._handoff_root(prev)
                    if root is not None:
                        candidates[cur.targets[0].id] = (root, cur)
        for flag, (root, true_stmt) in candidates.items():
            stmts = bool_assigns.get(flag, [])
            trues = [a for a in stmts if a.value.value is True]
            falses = [a for a in stmts if a.value.value is False]
            # Any OTHER write to the flag (non-constant, augmented, tuple
            # target, loop binding) disqualifies it — the correlation
            # must be total.
            def _target_nodes(n: ast.AST) -> list[ast.AST]:
                if isinstance(n, ast.Assign):
                    return list(n.targets)
                return [n.target]

            others = [
                n for n in ast.walk(fn)
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                  ast.For, ast.AsyncFor))
                and any(flag in _binding_names(t)
                        for t in _target_nodes(n))
                and n not in stmts
            ]
            if len(trues) == 1 and trues[0] is true_stmt and falses \
                    and not others:
                self.guard_flags[flag] = root
        # Collection-length value-flow refinement (ISSUE 13 satellite):
        # locals assigned as FILTERED VIEWS driven by the same predicate
        # have pairwise-equal lengths — `mask = (pid not in drop for pid
        # in X)` → `cols = cols.take(mask)` in one column plane, and
        # `deliveries_in = [deliveries[s] ... if pid not in drop]` in the
        # object plane, keep row-parallel residues by construction. So an
        # emptiness test on ONE of them (`if not len(cols): return`)
        # vacuously settles the PARTNERS' groups too: every row the
        # filter removed was settled by whoever produced `drop`
        # (settles-some), and zero residue on the tested side means zero
        # residue on the partner side. This retired the last
        # `ignore[settlement]` in _flush_columnar. Deliberately narrow:
        # predicates compare by exact ast.dump, must be anchored in a
        # free name, and `.take(mask)` inherits only a Name mask's
        # predicates.
        # ``pred_of``: name → its live filter-predicate dumps; ``takes``:
        # the subset of names whose predicates arrived through a
        # ``.take(mask)`` (a mask-filtered COLUMN view, not a list).
        # Linking is restricted to take-view ↔ comprehension pairs: two
        # plain comprehensions over different base collections can share
        # a predicate text without sharing a length, but a mask built
        # over the column view's own rows and a comprehension filtered by
        # the same anchored test are the paired-plane idiom this exists
        # for. A later REBIND of either name to an unfiltered value
        # clears its predicates — the contract follows the binding, not
        # the name.
        pred_of: dict[str, set[str]] = {}
        takes: set[str] = set()
        assigns = sorted(
            (n for n in ast.walk(fn)
             if isinstance(n, ast.Assign) and len(n.targets) == 1
             and isinstance(n.targets[0], ast.Name)),
            key=lambda n: (n.lineno, n.col_offset))
        for node in assigns:
            tgt = node.targets[0].id
            preds = _filter_predicates(node.value)
            v = node.value
            is_take = (isinstance(v, ast.Call)
                       and isinstance(v.func, ast.Attribute)
                       and v.func.attr == "take" and v.args
                       and isinstance(v.args[0], ast.Name))
            if is_take:
                # X = X.take(mask): the filtered view inherits the mask's
                # predicate identity.
                preds = preds | pred_of.get(v.args[0].id, set())
            if preds:
                pred_of[tgt] = pred_of.get(tgt, set()) | preds
                if is_take:
                    takes.add(tgt)
            else:
                # Rebound to something unfiltered: drop the stale
                # identity (and take-ness) or a fresh unsettled binding
                # would inherit the old emptiness correlation.
                pred_of.pop(tgt, None)
                takes.discard(tgt)
        self.len_partners: dict[str, set[str]] = {}
        for a, pa in pred_of.items():
            for b, pb in pred_of.items():
                if a == b or not (pa & pb):
                    continue
                if (a in takes) == (b in takes):
                    continue  # same plane: lengths not provably parallel
                self.len_partners.setdefault(a, set()).add(b)
        #: For linenos whose body settles/hands-off the loop target on
        #: EVERY path — computed per loop over a sub-CFG of the body alone
        #: so stale bindings from earlier loops cannot join in.  Filled by
        #: check() once the SourceFile is attached.
        self.settling_loops: set[int] = set()

    def _handoff_root(self, stmt: ast.AST) -> str | None:
        """The owned-group root ``stmt`` hands off, when it is a
        subscript/attribute store of an owned collection (the window-meta
        shape: ``self._inflight_meta[tok] = (dict(pairs), deliveries)``)."""
        if not (isinstance(stmt, ast.Assign) and stmt.targets
                and all(isinstance(t, (ast.Subscript, ast.Attribute))
                        for t in stmt.targets)):
            return None
        owned = {self.groups.find(n) for n in self.owned_seeds}
        for n in _bare_names(stmt.value):
            r = self.groups.find(n)
            if r in owned:
                return r
        return None

    def group_key(self, name: str) -> str:
        return "&" + self.groups.find(name)

    def owned_groups(self) -> set[str]:
        return {self.group_key(n) for n in self.owned_seeds}

    def partial_names(self) -> set[str]:
        """Names whose conditional settlement is contractual (settles-some
        params, their aliases, and loop targets over them)."""
        roots = {self.groups.find(n) for n in self.partial_seeds}
        out = set(roots) | set(self.partial_seeds)
        for n in self.tracked:
            if self.groups.find(n) in roots:
                out.add(n)
        for t, src in self._loop_src.items():
            if self.groups.find(src) in roots:
                out.add(t)
        return out


def _join_val(a: str, b: str) -> str:
    if a == b:
        return a
    if HELD in (a, b):
        return HELD
    pair = {a, b}
    if pair <= _OK_EXIT:
        return HANDLED
    return MIX


class _SettlementAnalysis(df.Analysis):
    """The typestate transfer over one function's CFG."""

    def __init__(self, scan: _FnScan, sf: SourceFile, qual: str):
        self.scan = scan
        self.sf = sf
        self.qual = qual
        self.findings: list[Finding] = []
        self.report = False
        self._seen: set[tuple] = set()
        #: Sub-CFG mode (per-loop body verdicts): replaces the entry state.
        self.entry_override: dict[str, str] | None = None

    # ---- reporting ---------------------------------------------------------

    def _flag(self, line: int, key: tuple, msg: str) -> None:
        if not self.report or key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(RULE, self.sf.path, line, msg,
                                     self.qual))

    # ---- lattice -----------------------------------------------------------

    def initial(self) -> dict[str, str]:
        if self.entry_override is not None:
            return dict(self.entry_override)
        state: dict[str, str] = {}
        params = [a.arg for a in (*self.scan.fn.args.posonlyargs,
                                  *self.scan.fn.args.args,
                                  *self.scan.fn.args.kwonlyargs)]
        for p in params:
            if p in self.scan.tracked:
                state[p] = PEND
        for key in self.scan.owned_groups():
            # Annotated collection params arm at entry; # owns: locals arm
            # at their assignment (absent until then).
            if key.lstrip("&") in params or any(
                    self.scan.groups.find(p) == key.lstrip("&")
                    for p in params):
                state[key] = PEND
        return state

    def join(self, a: str, b: str) -> str:
        return _join_val(a, b)

    # ---- events ------------------------------------------------------------

    def _settle(self, state: dict[str, str], var: str, line: int,
                check_double: bool, what: str) -> None:
        cur = state.get(var)
        if cur is None:
            return
        pretty = var.lstrip("&")
        if check_double and cur in (SETTLED, HANDLED):
            self._flag(line, ("double", var, line),
                       f"double-settle of {pretty!r}: already settled on "
                       f"every path reaching this {what} — the second "
                       f"settlement acks a delivery this function no longer "
                       f"owns")
        elif check_double and cur == MIX:
            self._flag(line, ("double-may", var, line),
                       f"possible double-settle of {pretty!r}: settled on "
                       f"SOME paths reaching this {what}")
        elif check_double and cur == ESCAPED:
            self._flag(line, ("double-esc", var, line),
                       f"settlement of {pretty!r} after ownership transfer: "
                       f"the new owner settles it again")
        state[var] = SETTLED

    def _escape(self, state: dict[str, str], var: str) -> None:
        if var in state:
            state[var] = ESCAPED

    def _apply_calls(self, stmt: ast.AST, state: dict[str, str]) -> None:
        for call in _calls_in_header(stmt):
            contract = self.scan.resolve(call)
            if contract is not None:
                args = self.scan._arg_exprs(call, contract)
                for pname, pos in contract.settles.items():
                    if pos not in args:
                        continue
                    for var in _names_in(args[pos]) & set(state):
                        if not var.startswith("&"):
                            self._settle(state, var, call.lineno, True,
                                         f"call to {_callee_name(call)}()")
                for pname, pos in contract.settles_coll.items():
                    if pos not in args:
                        continue
                    if self._settle_correlated(args[pos], state):
                        continue
                    hit = {self.scan.group_key(n)
                           for n in _names_in(args[pos])}
                    for key in hit & set(state):
                        self._settle(state, key, call.lineno, True,
                                     f"call to {_callee_name(call)}()")
            pair = _leaf_pair(call)
            if pair in _RAW_ACQUIRE:
                for var in list(state):
                    if not var.startswith("&") and _mentions_tag(call, var):
                        state[var] = HELD
            elif pair in _RAW_SETTLE:
                for var in list(state):
                    if not var.startswith("&") and _mentions_tag(call, var):
                        self._settle(state, var, call.lineno, False,
                                     "raw settle")
            # Container hand-off: append/submit of an expression mentioning
            # a tracked var transfers ownership — EXCEPT into the var's own
            # alias group (dedup keeps stay owned by the window).
            leaf = _callee_name(call)
            if leaf in _ESCAPE_METHODS:
                container = None
                if (isinstance(call.func, ast.Attribute)
                        and isinstance(call.func.value, ast.Name)):
                    container = call.func.value.id
                for arg in call.args:
                    for var in _bare_names(arg) & set(state):
                        if var.startswith("&"):
                            continue
                        if (container is not None
                                and self.scan.groups.find(container)
                                == self.scan.groups.find(
                                    self.scan._loop_src.get(var, var))):
                            continue  # kept within its own window group
                        self._escape(state, var)

    def _settle_correlated(self, arg: ast.AST,
                           state: dict[str, str]) -> bool:
        """Path-sensitive guard refinement (ISSUE 11 satellite): a
        ``settles: *`` argument of the shape ``None if flag else group``
        (or ``group if not flag else None``) where ``flag`` is a guard
        flag correlated with ``group``'s hand-off.  The correlation is
        exact by construction — ``flag`` is True iff the group escaped
        (its only True-assignment immediately follows the hand-off, with
        no raise edge in between since a constant store cannot raise) and
        the callee settles the collection exactly on the flag-False
        paths — so every path ends settled-or-escaped: HANDLED, with no
        conditional-settlement report.  Returns True when refined."""
        if not isinstance(arg, ast.IfExp):
            return False
        test, neg = arg.test, False
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            test, neg = test.operand, True
        if not isinstance(test, ast.Name):
            return False
        root = self.scan.guard_flags.get(test.id)
        if root is None:
            return False
        key = "&" + root
        if key not in state:
            return False
        escaped_branch = arg.orelse if neg else arg.body  # flag True value
        settle_branch = arg.body if neg else arg.orelse   # flag False value
        if not (isinstance(escaped_branch, ast.Constant)
                and escaped_branch.value is None):
            return False
        names = _names_in(settle_branch)
        if not names or any(self.scan.groups.find(n) != root
                            for n in names):
            return False
        state[key] = HANDLED
        return True

    def _check_leaves(self, state: dict[str, str], var: str, line: int,
                      where: str) -> None:
        """A variable's binding scope ends here (rebind or function exit):
        its obligations come due."""
        cur = state.get(var)
        if cur == HELD:
            self._flag(line, ("leak", var, line, where),
                       f"admission credit leak: {var.lstrip('&')!r} holds "
                       f"a credit "
                       f"(admission.admit) on a path that reaches {where} "
                       f"without ack/nack/shed/expire or release — the "
                       f"limiter tightens forever")
        elif (cur == MIX
              and var.lstrip("&") not in self.scan.partial_names()
              and var not in self.scan._loop_src):
            # Loop targets are exempt from MIX (their post-loop binding is
            # stale by construction); the collection-level checks own the
            # partial-settlement story for them.
            pretty = var.lstrip("&")
            self._flag(line, ("mix", var, line, where),
                       f"{pretty!r} is settled on some paths but not on a "
                       f"path reaching {where}: settle, hand off, or mark "
                       f"the helper '# settles-some:' if partial "
                       f"settlement is its contract")

    def _check_exit(self, node: df.Node, kind: str,
                    state: dict[str, str], cfg: df.CFG, dst: int) -> None:
        line = node.lineno or self.scan.fn.lineno
        if dst == cfg.raise_exit.idx:
            for var, cur in state.items():
                if cur == HELD:
                    self._flag(line, ("leak-exc", var, line),
                               f"admission credit leak on an exception "
                               f"path: {var!r} holds a credit when this "
                               f"statement raises — release it in a "
                               f"BaseException handler before the broker-"
                               f"level crash handler nacks")
            return
        if dst == cfg.exit.idx:
            for var in list(state):
                if var.startswith("&"):
                    if state[var] not in _OK_EXIT | {PEND}:
                        self._check_leaves(state, var, line, "a return")
                    if (state[var] == PEND
                            and var in self.scan.owned_groups()):
                        pretty = var.lstrip("&")
                        self._flag(line, ("coll-leak", var, line),
                                   f"window leak: collection {pretty!r} is "
                                   f"annotated settled-by-this-function "
                                   f"but a normal return is reached "
                                   f"without settling it")
                else:
                    self._check_leaves(state, var, line, "a return")

    # ---- dataflow hooks ----------------------------------------------------

    def transfer(self, node: df.Node, state: dict[str, str],
                 cfg: df.CFG) -> dict[str, str]:
        stmt = node.stmt
        if stmt is None:
            return state
        self._apply_calls(stmt, state)
        # Subscript/attribute stores hand the value off (window meta).
        if isinstance(stmt, ast.Assign):
            store_targets = [t for t in stmt.targets
                             if isinstance(t, (ast.Subscript, ast.Attribute))]
            if store_targets:
                for var in _bare_names(stmt.value):
                    if var in state:
                        self._escape(state, var)
                    key = self.scan.group_key(var)
                    if key in state:
                        state[key] = ESCAPED
            for t in stmt.targets:
                for var in _binding_names(t):
                    if var in self.scan.tracked:
                        self._check_leaves(state, var, stmt.lineno,
                                           "a rebind")
                        state[var] = PEND
                    gk = "&" + self.scan.groups.find(var)
                    if gk in self.scan.owned_groups():
                        # Local # owns: arming / alias rebind.
                        m = _comment_above(self.sf, stmt.lineno, _OWNS_RE)
                        if m and m.group(1) == var:
                            state.setdefault(gk, PEND)
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            for var in _bare_names(stmt.value) & set(state):
                state[var] = ESCAPED
        return state

    def edge(self, node: df.Node, kind: str, pre: dict[str, str],
             post: dict[str, str], cfg: df.CFG) -> dict[str, str] | None:
        stmt = node.stmt
        out = pre if kind == df.EXC else post
        if kind == df.EXC and stmt is not None:
            # Raw settle primitives are atomic for our purposes: an ack /
            # release that raises still discharged the obligation (both
            # are idempotent bookkeeping, and flagging them would turn
            # every settle loop into noise).  Annotated HELPERS stay
            # unsettled on their exception edge — they only promise
            # settlement when they return.
            for call in _calls_in_header(stmt):
                if _leaf_pair(call) in _RAW_SETTLE:
                    for var in list(out):
                        if (not var.startswith("&")
                                and _mentions_tag(call, var)):
                            out[var] = SETTLED
        dst = None
        for d, k in node.succ:
            if k == kind:
                dst = d  # any same-kind edge shares the state below
        if kind == df.ITER and isinstance(stmt, (ast.For, ast.AsyncFor)):
            for var in _binding_names(stmt.target):
                if var in self.scan.tracked:
                    # Only the HELD obligation survives a loop rebind check:
                    # MIX here is usually a stale binding from an earlier
                    # loop over the same name joining in — collection-level
                    # checks cover partial settlement.
                    if out.get(var) == HELD:
                        self._check_leaves(out, var, stmt.lineno,
                                           "the next loop iteration")
                    out[var] = PEND
        if kind == df.EXHAUSTED and isinstance(stmt, (ast.For,
                                                      ast.AsyncFor)):
            # Collection settle: a loop whose body settles its target on
            # every path (per-loop sub-CFG verdict, so stale joins from
            # earlier loops over the same name cannot pollute it) settles
            # the iterated collection.
            it_names = _names_in(stmt.iter)
            keys = {self.scan.group_key(n) for n in it_names} & set(out)
            if (keys and stmt.lineno not in self.scan.partial_loops
                    and stmt.lineno in self.scan.settling_loops):
                for key in keys:
                    self._settle(out, key, stmt.lineno, True,
                                 "settling loop")
        # Emptiness refinement: `if not window: return` — nothing left to
        # settle on the true branch.
        if isinstance(stmt, (ast.If, ast.While)):
            test = stmt.test
            neg = False
            if isinstance(test, ast.UnaryOp) and isinstance(test.op,
                                                            ast.Not):
                test = test.operand
                neg = True
            names = set()
            if isinstance(test, ast.Name):
                names = {test.id}
            elif (isinstance(test, ast.Call)
                  and _callee_name(test) == "len" and test.args):
                names = _names_in(test.args[0])
            empty_kind = df.TRUE if neg else df.FALSE
            if kind == empty_kind:
                # Length-parallel partners (ISSUE 13 satellite): an
                # emptiness test on a filtered view also empties every
                # same-predicate filtered partner — see the scan's
                # len_partners construction for the value-flow argument.
                expanded = set(names)
                for n in names:
                    expanded |= self.scan.len_partners.get(n, set())
                for n in expanded:
                    key = self.scan.group_key(n)
                    if key in out and out[key] in (PEND, MIX):
                        out[key] = SETTLED  # vacuously: it is empty
        # Exit obligations.
        if dst is not None:
            self._check_exit(node, kind, out, cfg, dst)
        return out


def _loop_settles(scan: _FnScan, sf: SourceFile, qual: str,
                  stmt: "ast.For | ast.AsyncFor") -> bool:
    """Does this loop's body settle (or hand off) its target on every path
    that completes an iteration?  Solved over a sub-CFG of the body alone
    with a fresh target binding, so stale states from earlier loops over
    the same name cannot join in.  ``continue`` paths are dead ends in the
    sub-CFG (optimistic); exception paths exit the loop and are the
    enclosing function's business."""
    targets = [v for v in _binding_names(stmt.target) if v in scan.tracked]
    if not targets:
        return False
    fake = ast.parse("def _loop_body():\n    pass").body[0]
    fake.body = list(stmt.body)
    cfg = df.CFG(fake)
    analysis = _SettlementAnalysis(scan, sf, qual)
    analysis.entry_override = {t: PEND for t in targets}
    exit_state = df.solve(cfg, analysis).get(cfg.exit.idx)
    if exit_state is None:
        return False
    # Tuple targets carry companions that never settle (the (pid, d) /
    # (d, tr) shapes): the loop settles its collection when the DELIVERY
    # member does on every completing path — at least one target fully
    # settled, none left mid-obligation or conditionally settled.
    vals = [exit_state.get(t) for t in targets]
    return (any(v in _OK_EXIT for v in vals)
            and not any(v in (HELD, MIX) for v in vals))


# ---- flush() return-contract refinement (ISSUE 12 satellite) ----------------
#
# The engine contract behind the non-pipelined columnar flush: a closure
# that DISPATCHES a window (``search_columns_async`` / ``search_async``)
# and returns ``engine.flush()`` yields exactly the windows in flight —
# here exactly ONE, because the dispatch immediately precedes the flush
# under the same lock.  So ``outs = await asyncio.to_thread(run_engine)``
# is a depth-1, never-empty sequence, and ``for tok, out in outs:`` runs
# its body exactly once.  Without that value-flow fact the typestate sees
# two false paths: a second iteration double-settling the window's
# deliveries, and a zero-iteration path leaving them unsettled.  The
# refinement DESUGARS such loops to their bodies (execute exactly once)
# before the CFG is built — the two PR 9 inline ignores this replaces are
# retired.  Deliberately narrow: the iterated name must be assigned
# exactly once, from ``to_thread(<closure>)`` where the closure both
# dispatches and returns a ``.flush()`` call, and the loop must have no
# break/continue/else.

_DISPATCH_LEAVES = frozenset({"search_columns_async", "search_async"})


def _flush_closure_names(fn: ast.AST) -> set[str]:
    """Local defs that dispatch a window and return ``engine.flush()``."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if (not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                or node is fn):
            continue
        dispatches = returns_flush = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                leaf = (dotted_name(sub.func) or "").rsplit(".", 1)[-1]
                if leaf in _DISPATCH_LEAVES:
                    dispatches = True
            elif (isinstance(sub, ast.Return)
                  and isinstance(sub.value, ast.Call)
                  and (dotted_name(sub.value.func) or "").endswith("flush")):
                returns_flush = True
        if dispatches and returns_flush:
            out.add(node.name)
    return out


def _singleton_flush_vars(fn: ast.AST, closures: set[str]) -> set[str]:
    """Names bound EXACTLY ONCE — by a plain ``(await) asyncio.to_thread(f)``
    assignment with ``f`` a dispatch-then-flush closure — and by NOTHING
    else (any other binding construct — loop target, with-item, walrus,
    aug/ann assignment — disqualifies: a rebound name no longer carries
    the flush() return contract)."""
    assigned: dict[str, int] = {}
    singles: set[str] = set()
    for node in ast.walk(fn):
        # Every binding construct counts against "exactly once".
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For,
                               ast.AsyncFor, ast.NamedExpr)):
            targets = [node.target]
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            targets = [item.optional_vars for item in node.items
                       if item.optional_vars is not None]
        for tgt in targets:
            for name in _binding_names(tgt):
                assigned[name] = assigned.get(name, 0) + 1
        if (not isinstance(node, ast.Assign) or len(node.targets) != 1
                or not isinstance(node.targets[0], ast.Name)):
            continue
        value = node.value
        if isinstance(value, ast.Await):
            value = value.value
        if (isinstance(value, ast.Call)
                and (dotted_name(value.func) or "").rsplit(".", 1)[-1]
                == "to_thread"
                and value.args and isinstance(value.args[0], ast.Name)
                and value.args[0].id in closures):
            singles.add(node.targets[0].id)
    return {name for name in singles if assigned.get(name) == 1}


class _SingletonLoopDesugar(ast.NodeTransformer):
    """Replace ``for … in <singleton-var>:`` with its body (runs once)."""

    def __init__(self, names: set[str]):
        self.names = names

    def _qualifies(self, node: "ast.For | ast.AsyncFor") -> bool:
        if not (isinstance(node.iter, ast.Name)
                and node.iter.id in self.names and not node.orelse):
            return False
        return not any(isinstance(sub, (ast.Break, ast.Continue))
                       for sub in ast.walk(node))

    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        if self._qualifies(node):
            return node.body
        return node

    visit_AsyncFor = visit_For


def _refine_flush_loops(fn: ast.AST) -> ast.AST:
    """The depth-1/never-empty flush() refinement: desugar qualifying
    loops on a COPY of the function (the shared tree must stay pristine
    for the other rules)."""
    closures = _flush_closure_names(fn)
    if not closures:
        return fn
    names = _singleton_flush_vars(fn, closures)
    if not names:
        return fn
    return _SingletonLoopDesugar(names).visit(copy.deepcopy(fn))


def check(sources: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in sources:
        # The settlement seams live in service/ by design; control/ joined
        # in ISSUE 11 — its executor/controller own engine hand-offs and
        # explicit lock pairings the same rules must prove.
        if not in_package(sf) or not any(
                seg in "/" + sf.path for seg in ("/service/", "/control/")):
            continue
        contracts = _collect_contracts(sf)
        for cls, fn in _iter_functions(sf.tree):
            qual = f"{cls}.{fn.name}" if cls else fn.name
            contract = contracts.get(qual) or _FnContract(fn)
            # Depth-1/never-empty flush() return contract (ISSUE 12):
            # loops over a dispatch-then-flush closure's result execute
            # exactly once — desugared before the CFG is built.
            fn = _refine_flush_loops(fn)
            scan = _FnScan(fn, contract, contracts, cls)
            scan._sf = sf
            # Re-scan # owns: locals now that the source is attached.
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], (ast.Name,
                                                         ast.Tuple))):
                    m = _comment_above(sf, node.lineno, _OWNS_RE)
                    if m:
                        name = m.group(1)
                        if name in _binding_names(node.targets[0]):
                            scan.owned_seeds.add(name)
            if not (scan.tracked or scan.owned_seeds):
                continue
            for node in ast.walk(fn):
                if (isinstance(node, (ast.For, ast.AsyncFor))
                        and _loop_settles(scan, sf, qual, node)):
                    scan.settling_loops.add(node.lineno)
            cfg = df.CFG(fn)
            analysis = _SettlementAnalysis(scan, sf, qual)
            df.solve_and_report(cfg, analysis)
            findings.extend(analysis.findings)
        findings.extend(_check_lock_pairing(sf))
    return findings


_iter_functions = df.iter_functions


# ---- lock-pairing -----------------------------------------------------------

def _lock_leaf(call: ast.Call) -> str | None:
    """The lock name when ``call`` is ``<...lock>.acquire()`` or
    ``.release()``."""
    if not isinstance(call.func, ast.Attribute):
        return None
    if call.func.attr not in ("acquire", "release"):
        return None
    name = dotted_name(call.func.value)
    leaf = name.rsplit(".", 1)[-1] if name else ""
    return leaf if leaf.lower().endswith("lock") else None


class _LockAnalysis(df.Analysis):
    def __init__(self, sf: SourceFile, qual: str):
        self.sf = sf
        self.qual = qual
        self.findings: list[Finding] = []
        self.report = False
        self._seen: set[tuple] = set()

    def _flag(self, line: int, key: tuple, msg: str) -> None:
        if not self.report or key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(LOCK_RULE, self.sf.path, line, msg,
                                     self.qual))

    def join(self, a: int | str, b: int | str):
        return a if a == b else "mix"

    def transfer(self, node: df.Node, state, cfg):
        stmt = node.stmt
        if stmt is None:
            return state
        for call in _calls_in_header(stmt):
            lock = _lock_leaf(call)
            if lock is None:
                continue
            held = state.get(lock, 0)
            if call.func.attr == "acquire":
                if held == 1:
                    self._flag(call.lineno, ("re", lock, call.lineno),
                               f"{lock}.acquire() while already held on "
                               f"every path here: asyncio/threading locks "
                               f"are not reentrant — this deadlocks")
                elif held == "mix":
                    self._flag(call.lineno, ("re?", lock, call.lineno),
                               f"{lock}.acquire() while held on SOME "
                               f"paths: a schedule exists that deadlocks")
                state[lock] = 1
            else:
                if held == 0:
                    self._flag(call.lineno, ("free", lock, call.lineno),
                               f"{lock}.release() without a matching "
                               f"acquire on every path here")
                state[lock] = 0
        return state

    def edge(self, node: df.Node, kind, pre, post, cfg):
        out = pre if kind == df.EXC else post
        if kind == df.EXC and node.stmt is not None:
            # release() is atomic for pairing purposes: even when the call
            # raises, the lock is no longer this path's to balance.
            for call in _calls_in_header(node.stmt):
                lock = _lock_leaf(call)
                if lock is not None and call.func.attr == "release":
                    out[lock] = 0
        for dst, k in node.succ:
            if k != kind:
                continue
            if dst == cfg.exit.idx or dst == cfg.raise_exit.idx:
                where = ("an exception path" if dst == cfg.raise_exit.idx
                         else "a return")
                for lock, held in out.items():
                    if held == 1:
                        self._flag(node.lineno or 0,
                                   ("exit", lock, node.lineno, where),
                                   f"{lock} still held on {where}: "
                                   f"release in a finally (or use "
                                   f"`async with`) so the pairing "
                                   f"balances on every path")
                    elif held == "mix":
                        self._flag(node.lineno or 0,
                                   ("exit?", lock, node.lineno, where),
                                   f"{lock} held on SOME paths reaching "
                                   f"{where}: the pairing is path-"
                                   f"dependent")
        return out


def _check_lock_pairing(sf: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    for cls, fn in _iter_functions(sf.tree):
        uses = any(_lock_leaf(c) for n in ast.walk(fn)
                   for c in ([n] if isinstance(n, ast.Call) else []))
        if not uses:
            continue
        qual = f"{cls}.{fn.name}" if cls else fn.name
        cfg = df.CFG(fn)
        analysis = _LockAnalysis(sf, qual)
        df.solve_and_report(cfg, analysis)
        findings.extend(analysis.findings)
    return findings
