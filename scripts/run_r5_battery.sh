#!/bin/bash
# Round-5 TPU measurement battery. Waits for the axon tunnel to recover,
# then runs every pending measurement in priority order, leaving logs in
# the repo root (*.log is gitignored; committed artifacts are written by
# the tools themselves, e.g. BENCH_CONFIGS.md).
set -u
cd /root/repo
export PYTHONPATH=/root/repo:/root/.axon_site

probe() {
  timeout 70 python -u -c \
    "import jax, jax.numpy as jnp; (jnp.ones(8)+1).block_until_ready()" \
    2>/dev/null
}

echo "[battery] waiting for tunnel ($(date +%H:%M))"
for i in $(seq 1 200); do
  if probe; then echo "[battery] tunnel up after $i probes ($(date +%H:%M))"; break; fi
  if [ "$i" = 200 ]; then echo "[battery] gave up"; exit 1; fi
  sleep 45
done

echo "[battery] 1/4 bench_configs --out BENCH_CONFIGS.md"
timeout 2400 python scripts/bench_configs.py --out BENCH_CONFIGS.md \
  > bench_configs_r5.json 2> bench_configs_r5.log
echo "[battery] bench_configs rc=$?"

echo "[battery] 2/4 full bench"
timeout 1800 python bench.py > bench_r5.json 2> bench_r5.log
echo "[battery] bench rc=$?"

echo "[battery] 3/4 latency mode"
timeout 1200 python bench.py --latency > bench_r5_latency.json 2> bench_r5_latency.log
echo "[battery] latency rc=$?"

echo "[battery] 4/4 rescanstall"
timeout 1200 python scripts/profile_stages.py --mode rescanstall \
  --window 2048 --iters 15 --reps 2 --rescan-every 10 \
  > /dev/null 2> rescanstall_r5.log
echo "[battery] rescanstall rc=$?"
echo "[battery] DONE ($(date +%H:%M))"
