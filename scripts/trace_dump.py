#!/usr/bin/env python
"""Fetch + pretty-print flight-recorder traces from a running service.

The /debug/traces endpoint (service/observability.py) returns raw JSON; this
helper renders a trace as a readable stage waterfall — the "why was THIS
request slow" workflow:

    # list the slow exemplars for one queue
    python scripts/trace_dump.py --queue matchmaking.search --slow

    # dump one trace by id (ids appear in the listing)
    python scripts/trace_dump.py --id 'matchmaking.search#1234'

    # recent lifecycle events (breaker trips, probes, chaos faults),
    # causally ordered by the spine seq (ISSUE 18)
    python scripts/trace_dump.py --events

    # incident bundles (ISSUE 18): the live ring, or one bundle offline
    python scripts/trace_dump.py --incident live
    python scripts/trace_dump.py --incident incident_inc-000001_failover.json

    # wait-vs-work gap waterfall (the ISSUE 6 attribution taxonomy)
    python scripts/trace_dump.py --queue matchmaking.search --slow --gaps

    # per-queue attribution summary (/debug/attribution)
    python scripts/trace_dump.py --attribution

    # match-quality & fairness summary (/debug/quality, ISSUE 8) — live,
    # or offline from a BENCH json's e2e_frontier rows
    python scripts/trace_dump.py --quality
    python scripts/trace_dump.py --quality --bench-json BENCH_r06.json

Stdlib (urllib) transport — usable inside the service container; the
``--gaps`` classifier imports matchmaking_tpu.service.attribution, which is
on the path wherever the service runs.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request


def _get(base: str, path: str, params: dict) -> dict:
    qs = urllib.parse.urlencode({k: v for k, v in params.items() if v})
    url = f"{base}{path}" + (f"?{qs}" if qs else "")
    try:
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read()).get("error", "")
        except Exception:
            detail = ""
        sys.exit(f"HTTP {e.code} from {url}: {detail}")
    except OSError as e:
        sys.exit(f"cannot reach {url}: {e} (is the service running with "
                 "metrics_port set?)")


def render_trace(tr: dict, out=sys.stdout) -> None:
    """One trace as a stage waterfall: absolute offset + per-stage delta."""
    marks = tr.get("marks", [])
    head = (f"{tr.get('trace_id', '?')}  queue={tr.get('queue', '?')} "
            f"player={tr.get('player_id') or '-'} "
            f"status={tr.get('status') or '-'} "
            f"total={tr.get('total_ms', 0):.3f}ms"
            + ("  [redelivered]" if tr.get("redelivered") else ""))
    print(head, file=out)
    if not marks:
        return
    t0 = marks[0][1]
    prev = t0
    for name, t in marks:
        off = (t - t0) * 1e3
        delta = (t - prev) * 1e3
        bar = "#" * min(40, max(0, int(delta)))
        print(f"  {off:10.3f}ms  +{delta:9.3f}ms  {name:<14} {bar}",
              file=out)
        prev = t
    print("", file=out)


def render_gaps(tr: dict, out=sys.stdout) -> None:
    """One trace as a wait-vs-work gap waterfall. Rendering only — the
    classification comes from attribution.decompose_marks, the SAME walk
    /debug/attribution uses, so the CLI can never disagree with the
    server-side decomposition."""
    from matchmaking_tpu.service.attribution import WAIT, decompose_marks

    marks = tr.get("marks", [])
    head = (f"{tr.get('trace_id', '?')}  queue={tr.get('queue', '?')} "
            f"player={tr.get('player_id') or '-'} "
            f"status={tr.get('status') or '-'} "
            f"total={tr.get('total_ms', 0):.3f}ms")
    print(head, file=out)
    if len(marks) < 2:
        return
    gaps, work_s, wait_s = decompose_marks(marks)
    for gap in gaps:
        delta = gap["ms"]
        bar = ("." if gap["kind"] == WAIT
               else "#") * min(40, max(0, int(delta)))
        print(f"  +{delta:9.3f}ms  {gap['kind']:<4} {gap['category']:<20} "
              f"{gap['from']}->{gap['to']:<14} {bar}", file=out)
    total = work_s + wait_s
    frac = wait_s / total if total else 0.0
    print(f"  = work {work_s * 1e3:.3f}ms + wait {wait_s * 1e3:.3f}ms "
          f"({frac:.0%} waiting)\n", file=out)


def render_attribution(body: dict, out=sys.stdout) -> None:
    """Per-queue attribution summary (/debug/attribution)."""
    print(f"SLO target: {body.get('slo_target_ms', 0):.1f} ms", file=out)
    for queue, entry in sorted(body.get("queues", {}).items()):
        wait_frac = entry.get("wait_fraction", 0.0)
        print(f"== {queue}: {entry.get('spans', 0)} spans, "
              f"p99 {entry.get('p99_total_ms')} ms, "
              f"{wait_frac:.0%} waiting", file=out)
        util = entry.get("device_util")
        if util:
            print(f"   device: idle {util['idle_fraction']:.1%}, "
                  f"occupancy {util['effective_occupancy']:.1%}, "
                  f"busy {util['device_busy_s']:.1f}s / "
                  f"idle {util['device_idle_s']:.1f}s", file=out)
        slo = entry.get("slo")
        if slo:
            print(f"   slo: attainment fast={slo['attainment_fast']} "
                  f"slow={slo['attainment_slow']} "
                  f"burn fast={slo['burn_fast']} slow={slo['burn_slow']}"
                  f"{'  BURNING' if slo.get('burning') else ''}", file=out)
        for name, cat in sorted(
                entry.get("categories", {}).items(),
                key=lambda kv: -kv[1]["total_s"]):
            print(f"   {cat['kind']:<4} {name:<22} "
                  f"{cat['total_s'] * 1e3:12.1f}ms total "
                  f"({cat['share']:6.1%})  p99 {cat['p99_ms']} ms  "
                  f"[{cat['traces']} traces / {cat['gaps']} gaps]", file=out)
        exemplar = next((v for k, v in entry.items()
                         if k.endswith("_exemplar")), None)
        if exemplar:
            print(f"   p99 exemplar {exemplar['trace_id']}: "
                  f"{exemplar['total_ms']:.1f}ms = "
                  f"work {exemplar['work_ms']:.1f}ms + "
                  f"wait {exemplar['wait_ms']:.1f}ms", file=out)
        print("", file=out)


def render_quality(body: dict, out=sys.stdout) -> None:
    """Per-queue quality/wait/disparity summary (/debug/quality shape)."""
    for queue, entry in sorted(body.get("queues", {}).items()):
        eng = entry.get("engine") or {}
        svc = entry.get("service") or {}
        print(f"== {queue}: {eng.get('samples', 0)} matched-player "
              f"samples", file=out)
        if eng.get("samples"):
            print(f"   quality: mean {eng.get('quality_mean')}  "
                  f"p10 {eng.get('quality_p10')}  "
                  f"p50 {eng.get('quality_p50')}  "
                  f"spread mean {eng.get('spread_mean')}", file=out)
            print(f"   wait-at-match: p50 {eng.get('wait_p50_s')}s  "
                  f"p90 {eng.get('wait_p90_s')}s  "
                  f"p99 {eng.get('wait_p99_s')}s", file=out)
            for b in eng.get("buckets", ()):
                if not b.get("count"):
                    continue
                print(f"     [{b['bucket']:>10}] n={b['count']:<7} "
                      f"quality {b.get('quality_mean')}  "
                      f"wait p90 {b.get('wait_p90_s')}s", file=out)
        disp = entry.get("disparity") or {}
        if disp:
            print(f"   disparity: quality gap {disp.get('quality_gap')} "
                  f"({disp.get('quality_gap_bucket') or '-'}), "
                  f"wait p90 gap {disp.get('wait_p90_gap_s')}s "
                  f"({disp.get('wait_gap_bucket') or '-'})", file=out)
        for tier, tq in (svc.get("tiers") or {}).items():
            print(f"   tier {tier}: n={tq.get('count')} "
                  f"quality mean {tq.get('quality_mean')} "
                  f"p10 {tq.get('quality_p10')}  "
                  f"wait p99 {tq.get('wait_p99_s')}s", file=out)
        slo = entry.get("slo_quality")
        if slo:
            print(f"   quality slo: target {slo.get('target_ms')}  "
                  f"attainment fast={slo.get('attainment_fast')} "
                  f"slow={slo.get('attainment_slow')}"
                  f"{'  BURNING' if slo.get('burning') else ''}", file=out)
        print("", file=out)


def render_frontier(doc: dict, out=sys.stdout) -> None:
    """The quality-vs-latency frontier from a BENCH json (e2e_frontier
    rows, ISSUE 8)."""
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    rows = doc.get("e2e_frontier", [])
    if not rows:
        print("no e2e_frontier rows in this BENCH json "
              "(run bench.py --e2e-quality)", file=out)
        return
    print("quality-vs-latency frontier (stricter threshold -> closer "
          "matches, longer waits):", file=out)
    print(f"  {'thr':>6} {'matched':>8} {'q_mean':>8} {'q_p10':>8} "
          f"{'spread':>8} {'waitp50ms':>10} {'waitp99ms':>10} "
          f"{'disparity':>10}", file=out)
    for r in sorted(rows, key=lambda r: r.get("threshold", 0.0)):
        print(f"  {r.get('threshold', 0):>6g} {r.get('matched', 0):>8} "
              f"{r.get('quality_mean')!s:>8} {r.get('quality_p10')!s:>8} "
              f"{r.get('spread_mean')!s:>8} "
              f"{r.get('wait_at_match_ms_p50')!s:>10} "
              f"{r.get('wait_at_match_ms_p99')!s:>10} "
              f"{r.get('quality_disparity')!s:>10}", file=out)
    for key in ("e2e_frontier_spread_monotone", "e2e_frontier_wait_monotone"):
        if key in doc:
            print(f"  {key}: {doc[key]}", file=out)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--queue", default="", help="filter by queue name")
    ap.add_argument("--id", default="", help="dump one trace by id")
    ap.add_argument("--slow", action="store_true",
                    help="show slow exemplars only (default: recent)")
    ap.add_argument("--n", type=int, default=16, help="traces per ring")
    ap.add_argument("--events", action="store_true",
                    help="show the lifecycle event log instead of traces")
    ap.add_argument("--incident", default="",
                    help="incident forensics (ISSUE 18): 'live' lists the "
                         "service's bundle ring (with --id, fetches one "
                         "bundle and renders its timeline); a file path "
                         "renders that bundle offline via postmortem.py")
    ap.add_argument("--gaps", action="store_true",
                    help="render traces as a wait-vs-work gap waterfall "
                         "(attribution taxonomy) instead of raw stages")
    ap.add_argument("--attribution", action="store_true",
                    help="per-queue attribution summary "
                         "(/debug/attribution)")
    ap.add_argument("--quality", action="store_true",
                    help="match-quality & fairness summary "
                         "(/debug/quality; with --bench-json, the "
                         "e2e_frontier rows of a BENCH artifact)")
    ap.add_argument("--scenario", action="store_true",
                    help="scenario-matrix artifact summary (ISSUE 13): "
                         "with --bench-json, the matrix table + per-cell "
                         "trajectory/autotune rendering (scripts/"
                         "scenario_report.py); live, the /debug/autotune "
                         "knob-decision ring")
    ap.add_argument("--cell", default="",
                    help="with --scenario --bench-json: one cell's full "
                         "story")
    ap.add_argument("--bench-json", default="",
                    help="read a BENCH json instead of a live service "
                         "(with --quality or --scenario)")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON instead of the waterfall rendering")
    args = ap.parse_args(argv)
    base = f"http://{args.host}:{args.port}"

    if args.scenario:
        if args.bench_json:
            import scenario_report

            doc = scenario_report._load(args.bench_json)
            if args.json:
                print(json.dumps(doc.get("scenario_matrix", []), indent=2))
            else:
                scenario_report.render(doc, cell_name=args.cell,
                                       full=not args.cell)
            return
        body = _get(base, "/debug/autotune", {"n": args.n})
        if args.json:
            print(json.dumps(body, indent=2))
            return
        print(f"autotune: target {body.get('target_p99_ms')} ms, "
              f"{body.get('moves')} move(s) over {body.get('ticks')} "
              f"tick(s); knobs {body.get('knobs')}")
        for d in body.get("decisions", []):
            print(f"  #{d.get('seq')} t={d.get('t')} {d.get('queue')} "
                  f"{d.get('knob')}: {d.get('from')} -> {d.get('to')} "
                  f"[{d.get('status')}] — {d.get('reason')}")
            if d.get("effect"):
                print(f"      effect: {d['effect']}")
        return

    if args.quality:
        if args.bench_json:
            with open(args.bench_json, encoding="utf-8") as f:
                doc = json.load(f)
            if args.json:
                print(json.dumps(doc.get("e2e_frontier", []), indent=2))
            else:
                render_frontier(doc)
            return
        body = _get(base, "/debug/quality", {"queue": args.queue})
        if args.json:
            print(json.dumps(body, indent=2))
        else:
            render_quality(body)
        return

    if args.attribution:
        body = _get(base, "/debug/attribution", {"queue": args.queue})
        if args.json:
            print(json.dumps(body, indent=2))
        else:
            render_attribution(body)
        return

    if args.incident:
        import postmortem

        if args.incident != "live":
            with open(args.incident, encoding="utf-8") as f:
                bundle = json.load(f)
            if args.json:
                print(json.dumps(postmortem.analyze(bundle), indent=2,
                                 sort_keys=True))
            else:
                postmortem.render(bundle, limit=args.n)
            return
        if args.id:
            bundle = _get(base, "/debug/incidents", {"id": args.id})
            if args.json:
                print(json.dumps(postmortem.analyze(bundle), indent=2,
                                 sort_keys=True))
            else:
                postmortem.render(bundle, limit=args.n)
            return
        body = _get(base, "/debug/incidents", {})
        if args.json:
            print(json.dumps(body, indent=2))
            return
        print(f"incidents: {body.get('captured', 0)} captured, "
              f"{body.get('dropped', 0)} dropped "
              f"(by class {body.get('by_class', {})}); "
              f"capture p99 {body.get('capture_ms_p99')} ms")
        for inc in body.get("incidents", []):
            print(f"  {inc['id']}  class={inc['class']:<20} "
                  f"queue={inc['queue'] or '-':<22} seq={inc['seq']:<7} "
                  f"{inc['spine_events']} spine events, "
                  f"captured in {inc['capture_ms']:.1f} ms")
        return

    if args.events:
        body = _get(base, "/debug/events",
                    {"queue": args.queue, "n": args.n})
        if args.json:
            print(json.dumps(body, indent=2))
            return
        # Causal order: rows arrive seq-sorted from the server; render
        # the seq + component so two events in the same millisecond read
        # in their true order (the old wall-clock print hid ties).
        for ev in body.get("events", []):
            print(f"#{ev.get('seq', 0):<6} {ev['t']:.3f}  "
                  f"[{ev.get('component', '?')}/{ev['kind']}] {ev['queue']}"
                  + (f" — {ev['detail']}" if ev.get("detail") else ""))
        return

    render = render_gaps if args.gaps else render_trace

    if args.id:
        tr = _get(base, "/debug/traces", {"id": args.id})
        if args.json:
            print(json.dumps(tr, indent=2))
        else:
            render(tr)
        return

    body = _get(base, "/debug/traces", {"queue": args.queue, "n": args.n})
    if args.json:
        print(json.dumps(body, indent=2))
        return
    ring = "slow" if args.slow else "recent"
    print(f"slow threshold: {body.get('slow_threshold_ms', 0):.1f} ms")
    for queue, rings in sorted(body.get("queues", {}).items()):
        traces = rings.get(ring, [])
        print(f"== {queue}: {len(traces)} {ring} trace(s)")
        for tr in traces:
            render(tr)


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        # downstream pager/head closed the pipe; exit quietly like other CLIs
        sys.stderr.close()
