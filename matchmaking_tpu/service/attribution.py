"""Critical-path attribution: decompose settled traces into work vs wait.

BENCH_r04 showed the engine sustaining 48k matches/s while the e2e service
path delivered 5.9k req/s — an 8x gap the flight recorder (PR 3) could only
*gesture* at: per-stage histograms say which stage is slow, but not whether
a request's latency was spent doing work (decode, pack, device step) or
WAITING for something (broker dwell, the batcher's window clock, a pipeline
slot, the publish loop). Closing the gap — and the Nitsum-style elastic
placement controller ROADMAP names next — needs that attribution as
numbers, continuously.

This module classifies every adjacent mark pair of a settled trace
(utils/trace.TraceContext) into a named category with a WORK/WAIT kind:

==================  =====  =====================================================
gap (prev → cur)    kind   meaning
==================  =====  =====================================================
enqueue→consume     wait   broker_dwell — queued in the broker before a
                           consumer picked it up
*→consume (redel.)  wait   redelivery_wait — nack/drop to redelivery pickup
consume→middleware  work   middleware — auth + validity checks
*→batch             work   ingress — decode/submit into the batcher
batch→flush         wait   batcher_hold — the window clock (max_wait_ms) or
                           windows queued ahead under saturation
flush→dispatch      wait   pipeline_slot_wait — engine-lock + pipeline-depth
                           backpressure + pre-dispatch sweeps
dispatch→h2d        work   pack_h2d — host pack + host→device transfer
h2d→device_step     work   device_step — the jitted kernel dispatch
dispatch→collect    work   engine_step — synchronous host-oracle engines
                           (no h2d/readback marks)
dispatch→oracle_…   work   oracle_step — delegated team/role oracle window
device_step→seal    wait   readback_group_wait — results waiting for their
                           readback group to fill/go stale
seal→collect        wait   readback_transfer — D2H in flight + collect poll
collect→publish     wait   publish_lag — outcome handling queue on the loop
*→dedup_replay      work   dedup_replay — terminal-response replay
*→shed / *→expired  work   admission — shed/expire decision + response
*→reject            work   reject — middleware/contract rejection
*→chaos_drop        wait   broker_dwell — the drop happened at the consume
                           point; the dwell before it is broker time
==================  =====  =====================================================

Per queue it maintains, for each category: gap count, cumulative seconds, a
log-bucketed histogram (utils/metrics.Histogram), and the number of distinct
traces touching the category (the replay-stable count: chunked windows emit
a variable number of h2d/device_step gaps per trace, but whether a trace
touched a category at all is a pure function of its lifecycle under seeded
chaos). Work + wait sums telescope to the enqueue→publish span exactly, by
construction — that identity is the smoke test scripts/check.sh runs.

When an SLO target is configured (ObservabilityConfig.slo_target_ms) it also
counts per-queue attainment: a settled trace is GOOD when it reached a
served outcome (not shed/expired/rejected/timeout) within the target.
Shed/expired requests burn the SLO on purpose — an objective met by
rejecting everyone is not met.

Loop-confined like the batcher and AdmissionController: ``observe`` runs on
the event loop (every trace-settle path does), never from worker threads —
there is deliberately no lock here.
"""

from __future__ import annotations

from typing import Any

from matchmaking_tpu.utils.metrics import DEFAULT_STAGE_BUCKETS, Histogram

WORK = "work"
WAIT = "wait"

#: Statuses that count as a served outcome for SLO attainment.
_SERVED_STATUSES = frozenset({"matched", "queued", "deduped"})

#: Classification keyed by the LATER mark of the pair (the mark a duration
#: is attributed to); pairs not covered here go through ``classify``'s
#: special cases, and genuinely unknown marks land in other_work/other_wait
#: so the work+wait identity still holds for novel mark vocabularies.
_BY_TARGET: dict[str, tuple[str, str]] = {
    "middleware": ("middleware", WORK),
    "batch": ("ingress", WORK),
    "flush": ("batcher_hold", WAIT),
    "dispatch": ("pipeline_slot_wait", WAIT),
    "h2d": ("pack_h2d", WORK),
    "device_step": ("device_step", WORK),
    "oracle_step": ("oracle_step", WORK),
    "readback_seal": ("readback_group_wait", WAIT),
    "collect": ("readback_transfer", WAIT),
    "publish": ("publish_lag", WAIT),
    "dedup_replay": ("dedup_replay", WORK),
    "reject": ("reject", WORK),
    "shed": ("admission", WORK),
    "expired": ("admission", WORK),
    "chaos_drop": ("broker_dwell", WAIT),
}

#: Marks whose presence means real work happened even when unknown pairs
#: surround them (conservative fallback kind for unknown TARGETS).
_KNOWN_WORK_MARKS = frozenset(
    name for name, (_, kind) in _BY_TARGET.items() if kind == WORK)


def classify(prev: str, cur: str) -> tuple[str, str]:
    """(category, kind) for the duration between marks ``prev`` and
    ``cur``. Total classification: every pair maps somewhere, so a trace's
    category durations always sum to its span."""
    if cur == "consume":
        return (("broker_dwell", WAIT) if prev == "enqueue"
                else ("redelivery_wait", WAIT))
    if cur == "collect" and prev in ("dispatch", "flush"):
        # Synchronous engines (host oracle, non-pipelined flush) bracket the
        # whole engine step with dispatch→collect and ship no device marks.
        return ("engine_step", WORK)
    got = _BY_TARGET.get(cur)
    if got is not None:
        return got
    return (("other_work", WORK) if cur in _KNOWN_WORK_MARKS
            else ("other_wait", WAIT))


def decompose_marks(
        marks) -> tuple[list[dict[str, Any]], float, float]:
    """THE gap walk: classify every adjacent pair of a mark sequence
    (``[(name, t), ...]`` — tuples or JSON lists) into the taxonomy.
    Returns (gaps, work_s, wait_s); work + wait telescopes to the span.
    Shared by ``decompose`` (server side) and the trace_dump ``--gaps``
    waterfall (CLI side) so the two can never disagree."""
    gaps: list[dict[str, Any]] = []
    work_s = 0.0
    wait_s = 0.0
    prev_name, prev_t = marks[0]
    for name, t in marks[1:]:
        dur = max(0.0, t - prev_t)
        category, kind = classify(prev_name, name)
        if kind == WORK:
            work_s += dur
        else:
            wait_s += dur
        gaps.append({"from": prev_name, "to": name, "category": category,
                     "kind": kind, "ms": round(dur * 1e3, 3)})
        prev_name, prev_t = name, t
    return gaps, work_s, wait_s


def decompose(trace) -> dict[str, Any]:
    """One trace's full wait-vs-work decomposition (JSON-ready): the
    per-gap waterfall plus work/wait sums that — by telescoping — equal the
    enqueue→publish span exactly."""
    gaps, work_s, wait_s = decompose_marks(trace.marks)
    return {
        "trace_id": trace.trace_id,
        "status": trace.status,
        "total_ms": round(trace.total_s * 1e3, 3),
        "work_ms": round(work_s * 1e3, 3),
        "wait_ms": round(wait_s * 1e3, 3),
        "gaps": gaps,
    }


class _Category:
    __slots__ = ("kind", "gaps", "traces", "total_s", "hist")

    def __init__(self, kind: str, buckets: tuple[float, ...]):
        self.kind = kind
        self.gaps = 0
        self.traces = 0
        self.total_s = 0.0
        self.hist = Histogram(buckets)


class _QueueAttribution:
    __slots__ = ("categories", "work_s", "wait_s", "spans", "total_hist",
                 "statuses", "slo_good", "slo_total")

    def __init__(self, buckets: tuple[float, ...]):
        self.categories: dict[str, _Category] = {}
        self.work_s = 0.0
        self.wait_s = 0.0
        self.spans = 0
        self.total_hist = Histogram(buckets)
        self.statuses: dict[str, int] = {}
        self.slo_good = 0
        self.slo_total = 0


class Attribution:
    """Per-queue wait-vs-work accounting over settled traces, fed by
    FlightRecorder.complete. All counters are monotone, so deltas between
    any two scrapes are well-defined (the telemetry ring samples them)."""

    def __init__(self, buckets: tuple[float, ...] | None = None,
                 slo_target_s: float = 0.0):
        self.buckets = tuple(buckets or DEFAULT_STAGE_BUCKETS)
        self.slo_target_s = slo_target_s
        self._queues: dict[str, _QueueAttribution] = {}

    def _queue(self, q: str) -> _QueueAttribution:
        qa = self._queues.get(q)
        if qa is None:
            qa = self._queues[q] = _QueueAttribution(self.buckets)
        return qa

    def observe(self, trace) -> None:
        qa = self._queue(trace.queue)
        marks = trace.marks
        touched: set[str] = set()
        prev_name, prev_t = marks[0]
        for name, t in marks[1:]:
            dur = max(0.0, t - prev_t)
            category, kind = classify(prev_name, name)
            cat = qa.categories.get(category)
            if cat is None:
                cat = qa.categories[category] = _Category(kind, self.buckets)
            cat.gaps += 1
            cat.total_s += dur
            cat.hist.observe(dur)
            if category not in touched:
                touched.add(category)
                cat.traces += 1
            if kind == WORK:
                qa.work_s += dur
            else:
                qa.wait_s += dur
            prev_name, prev_t = name, t
        qa.spans += 1
        total = trace.total_s
        qa.total_hist.observe(total)
        status = trace.status or "unknown"
        qa.statuses[status] = qa.statuses.get(status, 0) + 1
        if self.slo_target_s > 0:
            qa.slo_total += 1
            if status in _SERVED_STATUSES and total <= self.slo_target_s:
                qa.slo_good += 1

    # ---- reads -------------------------------------------------------------

    def slo_counts(self, queue: str) -> tuple[int, int]:
        """(good, total) settled-trace SLO counters for one queue — the
        cumulative series the burn-rate monitor differences."""
        qa = self._queues.get(queue)
        return (qa.slo_good, qa.slo_total) if qa is not None else (0, 0)

    def queue_totals(self, queue: str) -> dict[str, float]:
        """Monotone per-queue sums for the telemetry ring."""
        qa = self._queues.get(queue)
        if qa is None:
            return {"work_s": 0.0, "wait_s": 0.0, "spans": 0.0}
        return {"work_s": qa.work_s, "wait_s": qa.wait_s,
                "spans": float(qa.spans)}

    def snapshot(self, queue: str | None = None) -> dict[str, Any]:
        queues = [queue] if queue is not None else sorted(self._queues)
        out: dict[str, Any] = {}
        for q in queues:
            qa = self._queues.get(q)
            if qa is None:
                continue
            span_s = qa.work_s + qa.wait_s
            cats = {
                name: {
                    "kind": cat.kind,
                    "gaps": cat.gaps,
                    "traces": cat.traces,
                    "total_s": round(cat.total_s, 6),
                    "share": round(cat.total_s / span_s, 4) if span_s else 0.0,
                    "p99_ms": round(cat.hist.percentile(99) * 1e3, 3)
                    if cat.hist.count else None,
                }
                for name, cat in sorted(qa.categories.items())
            }
            entry: dict[str, Any] = {
                "spans": qa.spans,
                "work_s": round(qa.work_s, 6),
                "wait_s": round(qa.wait_s, 6),
                "wait_fraction": round(qa.wait_s / span_s, 4) if span_s else 0.0,
                "statuses": dict(sorted(qa.statuses.items())),
                "p99_total_ms": round(qa.total_hist.percentile(99) * 1e3, 3)
                if qa.total_hist.count else None,
                "categories": cats,
            }
            if self.slo_target_s > 0:
                entry["slo_good"] = qa.slo_good
                entry["slo_total"] = qa.slo_total
                entry["slo_attainment"] = (
                    round(qa.slo_good / qa.slo_total, 4)
                    if qa.slo_total else None)
            out[q] = entry
        return {"slo_target_ms": round(self.slo_target_s * 1e3, 3),
                "queues": out}
