"""Continuous in-proc telemetry: a bounded snapshot ring + SLO burn monitors.

/metrics is a point-in-time scrape and the flight recorder is per-request;
neither answers "what has the idle fraction / shed rate / stage p99 been
doing for the last minute" without an external Prometheus. The elastic
queue→device placement controller ROADMAP names next needs exactly that
signal IN-PROCESS — so this module keeps a small ring of periodic metric
snapshots (MatchmakingApp samples once per
``ObservabilityConfig.snapshot_interval_s``) and supports delta/rate
queries over any monotone counter in it.

On top of the ring, ``SloMonitor`` implements per-queue multi-window
burn-rate SLO evaluation (the Google SRE workbook shape Nitsum's admission
tiers presuppose): the attribution layer counts cumulative good/total
settled requests per queue (good = served within
``ObservabilityConfig.slo_target_ms``); the monitor differences those
counters over a FAST and a SLOW window and computes

    burn = (1 - attainment) / (1 - objective)

Burn 1.0 means the error budget is being spent exactly at the rate that
exhausts it by the end of the objective period; the monitor declares the
queue BURNING when both windows exceed ``slo_burn_threshold`` (the fast
window gives detection latency, the slow window de-flaps), emits
``slo_burn`` / ``slo_burn_clear`` EventLog events on transitions, and
publishes gauges so /metrics and /healthz show live burn state.

Scheduling note (matchlint ``determinism``): nothing here does wall-clock
deadline or next-sample arithmetic — snapshot timestamps are DATA
(``time.time()`` passed in by the sampler), window lookback is pure
``now - span`` arithmetic on those stored timestamps, and the sample cadence
itself is the app's ``asyncio.sleep`` loop.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Mapping, NamedTuple


class Delta(NamedTuple):
    """A counter delta over a ring window. Indexes [0]/[1] keep the old
    ``(value, span_s)`` tuple contract; ``reset`` (ISSUE 13 satellite)
    flags that the counter RESTARTED inside the window — an engine revive
    or breaker swap installs a fresh engine whose monotone counters begin
    again at 0, and a naive newest-minus-oldest difference would go
    negative (a negative "rate" fed a burn monitor or the autotuner is a
    corrupt signal, not a datum). When set, ``value`` is the
    reset-corrected increase: positive increments summed across the
    window, with each post-reset sample counted from 0 (the Prometheus
    ``increase()`` convention)."""

    value: float
    span_s: float
    reset: bool = False


class TelemetryRing:
    """Bounded ring of ``(seq, t, values)`` snapshots with delta/rate
    queries. Values are flat ``name -> float`` dicts; per-queue series use
    the same ``name[queue]`` convention as the metrics gauges so the prom
    flattener's label splitting applies unchanged."""

    def __init__(self, capacity: int = 512):
        self._snaps: deque[tuple[int, float, dict[str, float]]] = deque(
            maxlen=max(2, capacity))
        self._seq = 0

    def __len__(self) -> int:
        return len(self._snaps)

    def append(self, t: float, values: Mapping[str, float]) -> int:
        self._seq += 1
        self._snaps.append((self._seq, t, dict(values)))
        return self._seq

    def latest(self) -> dict[str, Any] | None:
        if not self._snaps:
            return None
        seq, t, values = self._snaps[-1]
        return {"seq": seq, "t": t, "values": values}

    def _window(self, span_s: float,
                now: float | None) -> tuple[tuple, tuple] | None:
        """(oldest-in-window, newest) snapshot pair, or None when fewer
        than two snapshots exist. A window longer than the ring falls back
        to the oldest retained snapshot — deltas stay well-defined, just
        over a shorter-than-requested span."""
        if len(self._snaps) < 2:
            return None
        newest = self._snaps[-1]
        t_end = newest[1] if now is None else now
        first = None
        for snap in self._snaps:
            if snap[1] >= t_end - span_s:
                first = snap
                break
        if first is None or first[0] == newest[0]:
            first = self._snaps[-2]
        return first, newest

    def delta(self, name: str, span_s: float,
              now: float | None = None) -> Delta | None:
        """:class:`Delta` of counter ``name`` over the last ``span_s``
        seconds of snapshots; None when the series is absent or fewer than
        two snapshots cover it. Counter restarts inside the window (engine
        revive / breaker swap — counters begin again at 0) are detected by
        walking the window's consecutive pairs: the delta is clamped to
        the reset-corrected increase and flagged ``reset=True`` instead of
        ever going negative."""
        pair = self._window(span_s, now)
        if pair is None:
            return None
        (seq0, t0, v0), (seq1, t1, v1) = pair
        if name not in v0 or name not in v1:
            return None
        span = max(0.0, t1 - t0)
        naive = v1[name] - v0[name]
        # Reset scan over the window's consecutive pairs — an endpoint
        # check alone is not enough (a reset can hide inside a window
        # whose endpoints still increased). The ring is seq-ascending,
        # so the walk skips to the window and stops at its end.
        inc = 0.0
        reset = False
        prev = None
        for seq, _t, vals in self._snaps:
            if seq > seq1:
                break
            if seq < seq0 or name not in vals:
                continue
            v = vals[name]
            if prev is not None:
                if v >= prev:
                    inc += v - prev
                else:
                    # Counter restarted: this sample counts from 0.
                    reset = True
                    inc += v
            prev = v
        if not reset:
            return Delta(naive, span, False)
        return Delta(inc, span, True)

    def rate(self, name: str, span_s: float,
             now: float | None = None) -> float | None:
        d = self.delta(name, span_s, now)
        if d is None or d[1] <= 0:
            return None
        return d[0] / d[1]

    def series(self, name: str, limit: int = 0) -> list[tuple[float, float]]:
        rows = [(t, values[name]) for _, t, values in self._snaps
                if name in values]
        return rows[-limit:] if limit else rows

    def snapshot(self, limit: int = 0,
                 prefixes: tuple[str, ...] = ()) -> list[dict[str, Any]]:
        """JSON-ready tail of the ring; ``prefixes`` PREFIX-filters the
        value keys (``idle_frac`` matches ``idle_frac[q]`` for every queue,
        ``slo`` matches every slo_* series) so a bench artifact can embed a
        trajectory without the full key set."""
        rows = []
        for seq, t, values in self._snaps:
            if prefixes:
                values = {k: v for k, v in values.items()
                          if any(k.startswith(p) for p in prefixes)}
            rows.append({"seq": seq, "t": round(t, 3), "values": values})
        return rows[-limit:] if limit else rows


class SloMonitor:
    """Per-queue multi-window burn-rate monitor over a pair of cumulative
    good/total counters in the telemetry ring. Default series are the
    latency-SLO ``slo_good[q]``/``slo_total[q]``; ``good_key``/``total_key``
    point a monitor at any other counter pair — the quality-SLO monitors
    (ISSUE 8) difference ``quality_good[q]``/``quality_total[q]`` with
    ``kind="quality"`` and are otherwise identical (same burn math, same
    events, same /healthz surfacing)."""

    def __init__(self, queue: str, target_ms: float, objective: float,
                 fast_window_s: float, slow_window_s: float,
                 burn_threshold: float = 1.0, events=None, metrics=None,
                 good_key: str | None = None, total_key: str | None = None,
                 kind: str = "latency"):
        self.queue = queue
        self.target_ms = target_ms
        self.kind = kind
        self._good_key = good_key or f"slo_good[{queue}]"
        self._total_key = total_key or f"slo_total[{queue}]"
        # Clamp away objective=1.0: a zero error budget makes burn infinite
        # on the first miss, which is an alerting footgun, not a policy.
        self.objective = min(0.9999, max(0.0, objective))
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.burn_threshold = burn_threshold
        self._events = events
        self._metrics = metrics
        self.burning = False
        self.burn_fast: float | None = None
        self.burn_slow: float | None = None
        self.attainment_fast: float | None = None
        self.attainment_slow: float | None = None

    def _attainment(self, ring: TelemetryRing, span_s: float,
                    now: float) -> float | None:
        good = ring.delta(self._good_key, span_s, now)
        total = ring.delta(self._total_key, span_s, now)
        if good is None or total is None or total[0] <= 0:
            return None  # no traffic settled in the window
        return max(0.0, min(1.0, good[0] / total[0]))

    def evaluate(self, ring: TelemetryRing, now: float) -> dict[str, Any]:
        """One evaluation tick (the app calls this right after each
        telemetry snapshot lands). Windows with no settled traffic read as
        not-burning: an idle queue is not missing its SLO."""
        budget = 1.0 - self.objective
        self.attainment_fast = self._attainment(ring, self.fast_window_s, now)
        self.attainment_slow = self._attainment(ring, self.slow_window_s, now)
        self.burn_fast = (None if self.attainment_fast is None
                          else (1.0 - self.attainment_fast) / budget)
        self.burn_slow = (None if self.attainment_slow is None
                          else (1.0 - self.attainment_slow) / budget)
        burning = (self.burn_fast is not None and self.burn_slow is not None
                   and self.burn_fast >= self.burn_threshold
                   and self.burn_slow >= self.burn_threshold)
        if burning != self.burning:
            self.burning = burning
            if self._events is not None:
                if burning:
                    target = (f"{self.target_ms:.0f} ms"
                              if self.kind == "latency"
                              else f"quality {self.target_ms:g}")
                    self._events.append(
                        "slo_burn", self.queue,
                        f"burn fast={self.burn_fast:.2f} "
                        f"slow={self.burn_slow:.2f} "
                        f"(threshold {self.burn_threshold:.2f}, target "
                        f"{target}, objective "
                        f"{self.objective:.4f})",
                        component="slo",
                        refs={"slo_kind": self.kind,
                              "burn_fast": round(self.burn_fast, 4),
                              "burn_slow": round(self.burn_slow, 4)})
                else:
                    self._events.append("slo_burn_clear", self.queue,
                                        "error budget burn back under "
                                        "threshold on both windows",
                                        component="slo",
                                        refs={"slo_kind": self.kind})
        if self._metrics is not None:
            q = self.queue
            self._metrics.set_gauge(f"slo_burning[{q}]",
                                    1.0 if self.burning else 0.0)
            if self.burn_fast is not None:
                self._metrics.set_gauge(f"slo_burn_fast[{q}]",
                                        round(self.burn_fast, 4))
            if self.burn_slow is not None:
                self._metrics.set_gauge(f"slo_burn_slow[{q}]",
                                        round(self.burn_slow, 4))
            if self.attainment_slow is not None:
                self._metrics.set_gauge(f"slo_attainment[{q}]",
                                        round(self.attainment_slow, 4))
        return self.snapshot()

    def snapshot(self) -> dict[str, Any]:
        rnd = lambda v: None if v is None else round(v, 4)  # noqa: E731
        return {
            "kind": self.kind,
            "target_ms": self.target_ms,
            "objective": self.objective,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "burn_threshold": self.burn_threshold,
            "attainment_fast": rnd(self.attainment_fast),
            "attainment_slow": rnd(self.attainment_slow),
            "burn_fast": rnd(self.burn_fast),
            "burn_slow": rnd(self.burn_slow),
            "burning": self.burning,
        }
