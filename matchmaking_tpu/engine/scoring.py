"""Scoring semantics — the single source of truth shared by the CPU oracle
and the TPU kernels (oracle-equivalence tests in ``tests/`` hold the two
implementations to these exact definitions).

The reference scores candidates by ELO distance against a
``rating_threshold`` (BASELINE.json north_star; SURVEY.md §2 C9). The
BASELINE configs add Glicko-2 rating-deviation weighting (#4): a high
combined deviation makes a given rating gap *less* certain, so the effective
distance shrinks by the Glicko g-function and uncertain players match more
freely.

All functions here are scalar/NumPy-broadcastable pure math, also valid
inside jit (no Python control flow on data).
"""

from __future__ import annotations

import math

# Glicko-2 g-function constant (q = ln 10 / 400, from the Glicko papers).
_Q = math.log(10.0) / 400.0
_G_COEFF = 3.0 * _Q * _Q / (math.pi * math.pi)


def glicko_g(rd_a, rd_b):
    """g(sqrt(rd_a^2 + rd_b^2)) — shrinks distances under uncertainty.

    Returns a factor in (0, 1]; 1.0 when both deviations are 0.
    """
    rd2 = rd_a * rd_a + rd_b * rd_b
    return 1.0 / (1.0 + _G_COEFF * rd2) ** 0.5


def distance(rating_a, rating_b, rd_a=0.0, rd_b=0.0, *, glicko2: bool = False):
    """Effective rating distance between two players.

    Plain mode: |Δ|. Glicko-2 mode: g·|Δ| (uncertainty-discounted).
    """
    delta = abs(rating_a - rating_b)
    if glicko2:
        return glicko_g(rd_a, rd_b) * delta
    return delta


def mutual_threshold(thr_a, thr_b):
    """A pair is valid only if the distance fits BOTH players' thresholds."""
    return min(thr_a, thr_b)


def quality(dist, thr_a, thr_b):
    """Match quality in [0, 1]: 1 at zero distance, 0 at the mutual limit."""
    limit = mutual_threshold(thr_a, thr_b)
    if limit <= 0.0:
        return 0.0
    return max(0.0, 1.0 - dist / limit)


def snake_signs(need: int) -> list[float]:
    """Sign of each ASCENDING-sorted window position in the team-sum
    difference (team A minus team B) under the snake split used by team
    queues (BASELINE config #3).

    The split assigns players in DESCENDING rating order: position j goes to
    team A iff j % 4 ∈ {0, 3} (A B B A A B B A ...). Ascending position i
    corresponds to descending position j = need-1-i. The sum difference
    depends only on the value multiset at each signed position, so
    equal-rating tie order cannot change it — the CPU oracle and the device
    kernel stay consistent however ties sort.

    Why the config-#3 team-sum constraint holds by construction: over the
    descending window the signed sum telescopes into an alternating series
    of DISJOINT consecutive gaps, (r0−r1) − (r2−r3) + (r4−r5) − …, each
    gap ≥ 0 and their total ≤ the window spread; an alternating series of
    non-negative terms is bounded by the sum of its positive terms, so
    |sum_A − sum_B| ≤ spread ≤ every member's threshold whenever the window
    is valid. Engines therefore enforce only the spread check; tests pin
    the balance property on formed matches.
    """
    return [1.0 if (need - 1 - i) % 4 in (0, 3) else -1.0 for i in range(need)]


def snake_split(members):
    """Split a full team window into (team_a, team_b) by the snake pattern.

    The ONE implementation of the split (oracle and device finalize both call
    it — a drifted modulus in a hand-copied loop would silently break
    oracle/device equivalence). Sorts by DESCENDING rating (stable, so ties
    keep caller order); descending position j goes to team A iff
    j % 4 ∈ {0, 3} — the pattern ``snake_signs`` above proves balanced.
    """
    ordered = sorted(members, key=lambda r: -r.rating)
    team_a, team_b = [], []
    for j, p in enumerate(ordered):
        (team_a if j % 4 in (0, 3) else team_b).append(p)
    return tuple(team_a), tuple(team_b)


def region_mode_compatible(region_a: str, mode_a: str, region_b: str, mode_b: str,
                           *, any_token: str = "*") -> bool:
    """Hard filters (BASELINE config #2): wildcard-or-equal on both axes."""
    region_ok = region_a == any_token or region_b == any_token or region_a == region_b
    mode_ok = mode_a == any_token or mode_b == any_token or mode_a == mode_b
    return region_ok and mode_ok
