"""Multi-chip matching: the pool sharded over a device mesh.

This is the rebuild's distributed story (SURVEY.md §2 "Distributed
communication backend", §5 "Long-context / sequence parallelism"): where the
reference scales by adding broker consumers on one BEAM node, here the pool's
slot dimension is sharded over a ``jax.sharding.Mesh`` axis ``"pool"`` and
each window is matched with XLA collectives over ICI:

1. every shard scores the (replicated) request window against its local pool
   slice and keeps the best candidate per pool block (fused max/argmax —
   no score materialization) — compute scales 1/n per chip;
2. the tiny B×n_blocks candidate lists are collected across shards, either
   with one ``all_gather`` (default; ≤ a few hundred KB) or with a
   ``ppermute`` ring in which each hop passes a neighbor's candidates —
   structurally ring attention with "scores" = masked −distance and
   "softmax" = the best-candidate reduction (SURVEY.md §5's long-context
   analog);
3. greedy pairing runs replicated on the merged lists (deterministic, so all
   shards agree), and each shard evicts its own slice of the matched slots.

The merged lists contain the global best candidate per request (the best
per block of its own shard), in canonical block order, so sharded and
single-device engines produce identical matches — pinned by tests on the
8-virtual-device CPU mesh.

Interface matches ``KernelSet`` (admit / evict / search_step over a pool
dict + padded batch dict), so ``TpuEngine`` swaps it in transparently when
``EngineConfig.mesh_pool_axis > 1``.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at the top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """shard_map across jax versions: new jax spells the replication-check
    kwarg ``check_vma``, 0.4.x spells it ``check_rep`` (and hosts the
    function under jax.experimental). One shim here serves every sharded
    kernel family (1v1, team, role)."""
    try:
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=check_vma)
    except TypeError:
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=check_vma)

from matchmaking_tpu.engine.kernels import (
    _NEG_INF,
    KernelSet,
    _effective_threshold,
    greedy_pair,
    unpack_batch,
)

AXIS = "pool"


def ring_all_gather(xs: tuple, n: int, *, axis_name: str = AXIS) -> tuple:
    """Collect each shard's arrays on every shard, in CANONICAL shard order,
    with a ``ppermute`` neighbor ring instead of one ``all_gather``.

    The ring-attention communication pattern shared by all three queue
    families (1v1 candidate merge, team/role frontier exchange): the
    ORIGINAL local arrays rotate one hop per step — D−1 hops, each talking
    only to a neighbor — and every received block is scattered into its
    source shard's slot, so the final buffers are identical on every shard.
    Per-hop ICI traffic is the size of ONE shard's arrays, independent of
    the global pool. Must run inside ``shard_map``.

    Returns one array per input with a leading shard axis: ``(n, *x.shape)``.
    """
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    outs = [jnp.zeros((n,) + x.shape, x.dtype).at[my].set(x) for x in xs]
    rots = list(xs)
    for h in range(1, n):
        rots = [lax.ppermute(r, axis_name, perm) for r in rots]
        src = (my - h) % n
        outs = [o.at[src].set(r) for o, r in zip(outs, rots)]
    return tuple(outs)


def tournament_merge_topk(bufs: list, key_fn):
    """Tournament-tree top-k merge of per-shard SORTED frontier buffers
    (ISSUE 14 — the PR 1 follow-up replacing the linear O(K·D) merge).

    ``bufs`` holds D buffers f32[C, k] (one per shard, canonical shard
    order), each already sorted by the 3-component lexicographic key
    ``key_fn(buf) -> (group i32[k], rating f32[k], gslot i32[k])`` —
    exactly the order ``teams.sorted_group_order`` gives a shard's
    frontier. Pairwise stable merges up a ⌈log2 D⌉-level tree, each node
    keeping only the top-k merged rows, so the merged working buffer is
    O(K·log D) across the tree instead of the O(K·D) concatenation the
    linear path sorts and forms windows over. Keys are recomputed from
    the merged ROWS at every level (never value-merged), so integer key
    components stay exact regardless of magnitude.

    Exactness contract (the ring step's host gate): whenever the GLOBAL
    active population fits in k rows, the merged top-k contains every
    active row in exactly the order the concat-and-sort linear merge
    yields — ties (equal group AND rating) resolve by global slot id,
    which is also the concat order (shard-ascending, slot-ascending
    within a shard). Each merge node is scatter-free: dense rank
    compares (k×k) + one-hot HIGHEST matmuls, the codebase's select
    idiom — every output column receives exactly one row across the two
    terms, so values are bit-preserved.

    Returns the merged f32[C, k] buffer (identical on every shard when
    the inputs are).
    """
    def lt(ka, kb):
        """Strict lexicographic (group, rating, gslot) less-than; ka
        components broadcast as columns, kb as rows."""
        ga, ra, sa = ka
        gb, rb, sb = kb
        return ((ga < gb)
                | ((ga == gb) & (ra < rb))
                | ((ga == gb) & (ra == rb) & (sa < sb)))

    def merge2(fa, fb):
        n = fa.shape[1]
        ka = tuple(c[:, None] for c in key_fn(fa))
        kb = tuple(c[None, :] for c in key_fn(fb))
        # Stable merge ranks: a-rows win ties (a is the lower-shard side,
        # matching concat order; ties beyond the full key are gslot-equal
        # inactive padding, where order is output-irrelevant).
        b_before_a = lt(kb, ka)                    # [i, j]: b_j < a_i
        pos_a = jnp.arange(n, dtype=jnp.int32) + b_before_a.sum(
            axis=1, dtype=jnp.int32)
        a_not_after_b = ~b_before_a                # [i, j]: a_i <= b_j
        pos_b = jnp.arange(n, dtype=jnp.int32) + a_not_after_b.sum(
            axis=0, dtype=jnp.int32)
        out_pos = jnp.arange(n, dtype=jnp.int32)
        sel_a = (pos_a[:, None] == out_pos[None, :]).astype(jnp.float32)
        sel_b = (pos_b[:, None] == out_pos[None, :]).astype(jnp.float32)
        return (jnp.matmul(fa, sel_a, precision=lax.Precision.HIGHEST)
                + jnp.matmul(fb, sel_b, precision=lax.Precision.HIGHEST))

    level = list(bufs)
    while len(level) > 1:
        nxt = [merge2(level[i], level[i + 1])
               for i in range(0, len(level) - 1, 2)]
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def pool_mesh(n_devices: int, devices: list | None = None) -> Mesh:
    """A 1-D mesh over the pool axis (multi-host: pass jax.devices())."""
    devs = (devices or jax.devices())[:n_devices]
    if len(devs) < n_devices:
        raise ValueError(
            f"mesh_pool_axis={n_devices} but only {len(devs)} devices visible"
        )
    return Mesh(np.array(devs), (AXIS,))


class ShardedKernelSet:
    """Compiled sharded step functions; same call surface as KernelSet."""

    def __init__(self, *, capacity: int, top_k: int, pool_block: int,
                 glicko2: bool, widen_per_sec: float, max_threshold: float,
                 mesh: Mesh, ring: bool = False, evict_bucket: int = 64,
                 pair_rounds: int = 8, bucket_frontier_k: int = 0):
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        if capacity % self.n_shards != 0:
            capacity += self.n_shards - capacity % self.n_shards
        self.capacity = capacity
        self.local_capacity = capacity // self.n_shards
        self.ring = ring
        self.evict_bucket = evict_bucket
        self.pair_rounds = pair_rounds
        # Per-shard compute reuses the single-device kernel internals on the
        # LOCAL slice (capacity = local_capacity). Block geometry is derived
        # from the GLOBAL capacity first: identical block boundaries are what
        # make sharded and single-device candidate lists — and therefore
        # matches — identical (test_sharded_equals_single_device). When the
        # global block doesn't fit the local slice (pool_block >
        # local_capacity), blocks shrink to the slice and the two engines'
        # fallback candidates may legally differ under contention (the best
        # candidate, and so oracle semantics, are unaffected).
        from matchmaking_tpu.engine.kernels import effective_pool_block

        global_block = effective_pool_block(capacity, pool_block, top_k)
        self.local = KernelSet(
            capacity=self.local_capacity, top_k=top_k,
            pool_block=min(global_block, self.local_capacity),
            glicko2=glicko2,
            widen_per_sec=widen_per_sec, max_threshold=max_threshold,
            exact_block=True,
        )
        self.top_k = self.local.top_k
        self.widen_per_sec = widen_per_sec
        self.max_threshold = max_threshold
        #: Per-bucket top-K frontier exchange (ISSUE 14): > 0 enables the
        #: bucketed sharded step family — each shard compacts every LOCAL
        #: pool block (= rating bucket) into its top-K active rows and ONLY
        #: those frontiers cross the shard boundary (ppermute ring), so ICI
        #: traffic is occupancy-shaped (O(nb·K·D)) and per-window formation
        #: scores O(B · nb·K) frontier rows instead of O(B · P). Bit-exact
        #: vs the flat/dense candidate lists whenever every bucket's active
        #: population fits K rows — the host checks the mirror's per-segment
        #: occupancy (PlayerPool.segment_max, a conservative superset of
        #: device-active) per window and falls back to ``search_step_packed``
        #: above it. The value here is the LADDER CEILING; compiled steps
        #: are cached per actual K (``bucket_step``), so the engine sizes K
        #: adaptively from observed occupancy without recompiling the pool.
        self.bucket_frontier_k = (min(max(1, bucket_frontier_k),
                                      self.local.pool_block)
                                  if bucket_frontier_k > 0 else 0)
        self._bucket_steps: dict[int, Any] = {}
        self.local_blocks = self.local.n_blocks
        self.global_blocks = self.n_shards * self.local.n_blocks

        pool_spec = {k: P(AXIS) for k in
                     ("rating", "rd", "region", "mode", "threshold",
                      "enqueue_t", "active")}
        rep = P()
        batch_spec = {k: rep for k in
                      ("slot", "rating", "rd", "region", "mode", "threshold",
                       "enqueue_t", "valid")}

        self.search_step = jax.jit(
            _shard_map(
                self._search_step_shard, mesh=mesh,
                in_specs=(pool_spec, batch_spec, rep),
                out_specs=(pool_spec, rep, rep, rep),
                check_vma=False,
            ),
            donate_argnums=0,
        )
        self.admit = jax.jit(
            _shard_map(self._admit_shard, mesh=mesh,
                       in_specs=(pool_spec, batch_spec), out_specs=pool_spec,
                       check_vma=False),
            donate_argnums=0,
        )
        self.evict = jax.jit(
            _shard_map(self._evict_shard, mesh=mesh,
                       in_specs=(pool_spec, rep), out_specs=pool_spec,
                       check_vma=False),
            donate_argnums=0,
        )
        # Packed I/O variants (one replicated f32[9,B] in / f32[3,B] out —
        # single H2D/D2H RPC per window; see pool.PACKED_ROWS).
        self.search_step_packed = jax.jit(
            _shard_map(
                self._search_step_packed_shard, mesh=mesh,
                in_specs=(pool_spec, rep), out_specs=(pool_spec, rep),
                check_vma=False,
            ),
            donate_argnums=0,
        )
        self.admit_packed = jax.jit(
            _shard_map(
                lambda pool, packed: self._admit_shard(pool, unpack_batch(packed)),
                mesh=mesh, in_specs=(pool_spec, rep), out_specs=pool_spec,
                check_vma=False,
            ),
            donate_argnums=0,
        )

    def _search_step_packed_shard(self, pool, packed):
        batch = unpack_batch(packed)
        now = packed[8, 0]
        pool, out_q, out_c, out_d = self._search_step_shard(pool, batch, now)
        out = jnp.stack([out_q.astype(jnp.float32),
                         out_c.astype(jnp.float32), out_d])
        return pool, out

    # ---- helpers (run per shard, inside shard_map) ------------------------

    def _localize_batch(self, batch: dict[str, Any]) -> dict[str, Any]:
        """Global slot ids → this shard's local ids (others → sentinel)."""
        offset = lax.axis_index(AXIS) * self.local_capacity
        local = batch["slot"] - offset
        mine = (local >= 0) & (local < self.local_capacity)
        return dict(batch, slot=jnp.where(mine, local, self.local_capacity))

    def _admit_shard(self, pool, batch):
        return self.local._admit(pool, self._localize_batch(batch))

    def _evict_shard(self, pool, slots):
        offset = lax.axis_index(AXIS) * self.local_capacity
        local = slots - offset
        mine = (local >= 0) & (local < self.local_capacity)
        return self.local._evict(pool, jnp.where(mine, local, self.local_capacity))

    def _global_merge(self, vals, gidx):
        """Concatenate per-shard best-per-block candidate lists on every
        shard, in CANONICAL shard order.

        Canonical order matters: greedy pairing breaks exact-score ties by
        candidate position, so a shard-dependent merge order would let tied
        candidates win on some shards and lose on others — the "replicated"
        pairing would then diverge across shards and desynchronize device
        state from the host mirror (exact distance ties are common with
        integer ratings). Because shard s's local blocks cover the global
        slot range [s·localP, (s+1)·localP) in order, the merged list is
        exactly the single-device kernel's best-per-block list whenever the
        block geometry matches — pinned by test_sharded_equals_single_device.
        """
        n = self.n_shards
        b, k = vals.shape
        if not self.ring:
            av = lax.all_gather(vals, AXIS)            # (n, B, k), axis order
            ai = lax.all_gather(gidx, AXIS)
        else:
            # Ring collect (shared shard-exchange helper — the same
            # ppermute ring the team/role frontier paths ride).
            av, ai = ring_all_gather((vals, gidx), n)
        av = jnp.moveaxis(av, 0, 1).reshape(b, n * k)
        ai = jnp.moveaxis(ai, 0, 1).reshape(b, n * k)
        return av, ai

    # ---- the sharded step -------------------------------------------------

    def _search_step_shard(self, pool, batch, now):
        lk = self.local
        offset = lax.axis_index(AXIS) * self.local_capacity

        # 1. Admit this shard's slice of the window.
        local_batch = self._localize_batch(batch)
        pool = lk._admit(pool, local_batch)

        # 2. Local best-per-block candidates against the local pool slice.
        #    The batch keeps its GLOBAL slot ids for self-masking: compare
        #    against global index.
        q_thr_eff = _effective_threshold(
            batch["threshold"], batch["enqueue_t"], now,
            self.widen_per_sec, self.max_threshold,
        )
        # Self-mask needs global ids: shift the batch slots into the local
        # frame (non-local ids land outside [0, local_capacity) and thus
        # never self-mask, which is correct — the self slot lives on exactly
        # one shard).
        vals, idxs_local = lk._candidates(
            dict(batch, slot=batch["slot"] - offset), q_thr_eff, pool, now
        )
        gidx = jnp.where(idxs_local >= self.local_capacity,
                         self.capacity, idxs_local + offset)

        # 3. Canonical-order global candidate lists on every shard
        #    (all_gather or ppermute ring).
        mv, mi = self._global_merge(vals, gidx)

        # 4. Replicated greedy pairing on global ids (deterministic — every
        #    shard computes the identical pairing, no broadcast needed).
        out_q, out_c, out_d = greedy_pair(mv, mi, batch["slot"], self.capacity,
                                          self.pair_rounds)

        # 5. Each shard evicts its slice of the matched slots (compare-masked
        #    via the local kernel's scatter-free eviction).
        matched = jnp.concatenate([out_q, out_c]) - offset
        mine = (matched >= 0) & (matched < self.local_capacity)
        pool = lk._evict(pool, jnp.where(mine, matched, self.local_capacity))
        return pool, out_q, out_c, out_d

    # ---- bucket-frontier step family (ISSUE 14) ---------------------------

    def bucket_step(self, k: int):
        """The compiled bucket-frontier step for frontier width ``k``
        (lazily compiled, cached per K — the adaptive-K ladder's entries).
        Same call surface as ``search_step_packed`` but the result is
        f32[4, B]: rows 0-2 the flat layout, row 3 the touched-slot count.
        Only valid while every bucket's live population fits ``k`` rows
        (host-gated via the mirror's per-segment occupancy)."""
        k = min(max(1, k), self.local.pool_block)
        fn = self._bucket_steps.get(k)
        if fn is None:
            pool_spec = {f: P(AXIS) for f in
                         ("rating", "rd", "region", "mode", "threshold",
                          "enqueue_t", "active")}
            rep = P()
            fn = jax.jit(
                _shard_map(
                    functools.partial(self._search_step_bucket_shard, k=k),
                    mesh=self.mesh, in_specs=(pool_spec, rep),
                    out_specs=(pool_spec, rep), check_vma=False),
                donate_argnums=0)
            self._bucket_steps[k] = fn
        return fn

    def _pack_block_frontier(self, pool, k: int):
        """Per-LOCAL-block top-k frontier: f32[nb_local, 8, k] rows =
        (rating, rd, region, mode, threshold, enqueue_t, active, gslot),
        active rows first in slot-ascending order (stable argsort of the
        inactive flag), padding rows carry the capacity sentinel. When a
        block holds ≤ k active rows the frontier contains ALL of them —
        the no-overflow precondition the host gate enforces. Must run
        inside shard_map."""
        lk = self.local
        blk = lk.pool_block
        offset = lax.axis_index(AXIS) * self.local_capacity
        fields = ("rating", "rd", "region", "mode", "threshold", "enqueue_t")

        def body(_, blk_i):
            start = blk_i * blk
            act = lax.dynamic_slice_in_dim(pool["active"], start, blk)
            top = jnp.argsort(~act, stable=True)[:k]
            rows = [lax.dynamic_slice_in_dim(pool[f], start, blk)[top]
                    .astype(jnp.float32) for f in fields]
            a = act[top]
            gslot = jnp.where(a, start + top + offset,
                              self.capacity).astype(jnp.float32)
            return None, jnp.stack(rows + [a.astype(jnp.float32), gslot])

        _, fr = lax.scan(body, None,
                         jnp.arange(lk.n_blocks, dtype=jnp.int32))
        return fr

    def _search_step_bucket_shard(self, pool, packed, k: int):
        """One window via per-bucket top-K frontier exchange: local admit →
        per-block frontier compaction (O(P/D) column reads) → ppermute ring
        (ONLY frontiers cross the shard boundary) → replicated bucket-local
        scoring over the merged nb_global·K frontier rows → replicated
        pairing → local eviction. Bit-exact vs the dense candidate lists
        while no bucket overflows K (host-gated)."""
        lk = self.local
        batch = unpack_batch(packed)
        now = packed[8, 0]
        b = batch["rating"].shape[0]
        offset = lax.axis_index(AXIS) * self.local_capacity

        pool = lk._admit(pool, self._localize_batch(batch))
        fr = self._pack_block_frontier(pool, k)
        (buf,) = ring_all_gather((fr,), self.n_shards)
        # (n, nb_local, 8, k) → (nb_global, 8, k) in canonical block order.
        fr_g = buf.reshape(self.global_blocks, 8, k)

        q_thr_eff = _effective_threshold(
            batch["threshold"], batch["enqueue_t"], now,
            self.widen_per_sec, self.max_threshold,
        )

        def body(_, fb):
            block = {"rating": fb[0], "rd": fb[1],
                     "region": fb[2].astype(jnp.int32),
                     "mode": fb[3].astype(jnp.int32),
                     "threshold": fb[4], "enqueue_t": fb[5],
                     "active": fb[6] > 0.5}
            gslot = fb[7].astype(jnp.int32)
            not_self = batch["slot"][:, None] != gslot[None, :]
            scores = lk._score_block(batch, q_thr_eff, block, 0, now,
                                     not_self=not_self)
            v, i = lk._block_best(scores)
            return None, (v, jnp.take(gslot, i))

        _, (vs, is_) = lax.scan(body, None, fr_g)
        vals = vs.T                                 # (B, nb_global)
        idxs = jnp.where(vals > _NEG_INF, is_.T, self.capacity)

        out_q, out_c, out_d = greedy_pair(vals, idxs, batch["slot"],
                                          self.capacity, self.pair_rounds)

        matched = jnp.concatenate([out_q, out_c]) - offset
        mine = (matched >= 0) & (matched < self.local_capacity)
        pool = lk._evict(pool, jnp.where(mine, matched, self.local_capacity))

        touched = jnp.float32(min(self.global_blocks * k, self.capacity))
        out = jnp.concatenate([
            jnp.stack([out_q.astype(jnp.float32),
                       out_c.astype(jnp.float32), out_d]),
            jnp.broadcast_to(touched, (1, b))])
        return pool, out

    # ---- placement --------------------------------------------------------

    def place_pool(self, arrays: dict[str, np.ndarray]) -> dict[str, jnp.ndarray]:
        sharding = NamedSharding(self.mesh, P(AXIS))
        return {k: jax.device_put(jnp.asarray(v), sharding)
                for k, v in arrays.items()}


@functools.lru_cache(maxsize=None)
def sharded_kernel_set(capacity: int, top_k: int, pool_block: int,
                       glicko2: bool, widen_per_sec: float,
                       max_threshold: float, n_shards: int,
                       ring: bool, pair_rounds: int = 8,
                       device_ids: "tuple[int, ...] | None" = None,
                       bucket_frontier_k: int = 0,
                       ) -> ShardedKernelSet:
    """``device_ids`` (elastic placement, ISSUE 11): the logical device
    indices the pool mesh spans — None keeps the pre-placement default
    (the first ``n_shards`` of ``jax.devices()``).  Part of the cache key:
    the same shape promoted onto a different chip pair is a different
    compiled set."""
    devices = None
    if device_ids is not None:
        if len(device_ids) != n_shards:
            raise ValueError(
                f"device_ids {device_ids} must match n_shards={n_shards}")
        all_devs = jax.devices()
        devices = [all_devs[i] for i in device_ids]
    return ShardedKernelSet(
        capacity=capacity, top_k=top_k, pool_block=pool_block, glicko2=glicko2,
        widen_per_sec=widen_per_sec, max_threshold=max_threshold,
        mesh=pool_mesh(n_shards, devices), ring=ring, pair_rounds=pair_rounds,
        bucket_frontier_k=bucket_frontier_k,
    )
