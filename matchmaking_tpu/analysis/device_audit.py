"""``device``: the jaxpr-level device-path audit.

The recompile rule (recompile.py) proves each kernel family's TRACE is
stable; this rule audits what the trace actually DOES.  Everything here is
trace-only — ``jax.make_jaxpr`` / ``jax.eval_shape`` under the canonical
small configs recompile.py already defines — so the lint node never
initializes a TPU backend and never executes a kernel (BENCH_CONFIGS.md:
lint stays off the bench path).

Static half (AST over the kernel/engine modules):

- **host-sync inside kernel modules** — ``.item()`` / ``.tolist()`` /
  ``np.asarray`` / ``jax.device_get`` / ``block_until_ready`` inside any
  function of a kernel module: under jit these either crash at trace time
  (concretization) or, on the host paths threaded through the same
  modules, silently serialize the dispatch pipeline.  ``__init__`` bodies
  and module level are exempt (host-side setup: bucket edges, config).
- **donated-buffer use-after-donation** — the engine's step kernels all
  take ``donate_argnums=0`` (the pool buffer is donated).  Flow-sensitive
  over the dataflow CFG: reading the variable that was passed as the
  donated argument after the call — without rebinding it from the call's
  result — is a use of a dead buffer (``RuntimeError: invalid buffer`` on
  device, silent stale data under some backends).

Trace half (per kernel family under canonical configs):

- **host callbacks inside jit** — ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` / infeed/outfeed primitives anywhere in a kernel
  family's jaxpr: a host round trip per window inside the hot step.
- **dtype preservation & drift** — each step must return the pool with
  EXACTLY the input dtypes (an upcast silently doubles HBM and breaks
  donation reuse), and the shared pool fields must carry the same dtypes
  across kernel FAMILIES (1v1 / glicko2 / team / role) — drift between
  families breaks checkpoint/restore and the breaker's engine swaps.
- **padded-lane contamination** (the QualityAccumKernel shape) — masked
  lanes carry the ``+inf`` dist sentinel; ``0 × inf = NaN``, so a masked
  SUM is NOT a sanitizer — only a ``select``/``where`` gated on a
  validity mask is.  Checked by forward taint over the jaxpr: the
  sentinel-carrying input taints everything it reaches EXCEPT through a
  ``select_n`` whose predicate derives from a sentinel comparison and
  which offers at least one clean branch.  Gather indices do not
  propagate taint (clipped index reads return real pool values).
- **ppermute ring audit** — the sharded families' ``ring_all_gather``
  hops must use one consistent permutation forming a single cycle that
  covers the whole mesh axis (a split or inconsistent ring silently
  drops shards' candidates).  Runs only when ≥ 2 devices are visible
  (the pytest CPU mesh has 8; a bare CLI run skips it).
"""

from __future__ import annotations

import ast
from typing import Any, Callable

from matchmaking_tpu.analysis import dataflow as df
from matchmaking_tpu.analysis.core import (
    Finding,
    SourceFile,
    dotted_name,
    qualname_of,
)
from matchmaking_tpu.analysis.recompile import (
    KERNEL_MODULES,
    _canonical_packed,
    _canonical_pool,
)

RULE = "device"

#: Engine modules whose kernel CALL SITES get the donation audit.
ENGINE_PREFIX = "matchmaking_tpu/engine/"

#: Dotted suffixes that host-sync (full readback / blocking).
_HOST_SYNC_CALLS = {
    "np.asarray": "full-array host readback",
    "numpy.asarray": "full-array host readback",
    "jax.device_get": "blocking D2H transfer",
}
_HOST_SYNC_METHODS = {
    "item": "host-syncs a device scalar (trace-time crash under jit)",
    "tolist": "host-syncs the whole array",
    "block_until_ready": "blocks on device completion",
}

#: Kernel attributes compiled with ``donate_argnums=0`` (the pool arg).
DONATING_KERNELS = frozenset({
    "admit", "evict", "search_step", "admit_packed", "search_step_packed",
    "search_step_packed_nofilter", "search_step_packed_rescan",
    "search_step_packed_ring",
})

#: jaxpr primitives that round-trip through the host.
_CALLBACK_PRIMS = ("callback", "infeed", "outfeed")


# ---- static: host-sync in kernel modules ------------------------------------

class _HostSyncScanner(ast.NodeVisitor):
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.findings: list[Finding] = []
        self._stack: list[ast.AST] = []

    def _in_scope(self) -> bool:
        fns = [n for n in self._stack
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        return bool(fns) and fns[-1].name != "__init__"

    def visit_ClassDef(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    def _fn(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _fn
    visit_AsyncFunctionDef = _fn

    def visit_Call(self, node: ast.Call) -> None:
        if self._in_scope():
            name = dotted_name(node.func)
            hint = None
            what = name
            for suffix, h in _HOST_SYNC_CALLS.items():
                if name == suffix or name.endswith("." + suffix):
                    hint = h
                    break
            if hint is None and isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                if (meth in _HOST_SYNC_METHODS and not node.args
                        and not node.keywords):
                    hint = _HOST_SYNC_METHODS[meth]
                    what = f".{meth}()"
            if hint is not None:
                self.findings.append(Finding(
                    RULE, self.sf.path, node.lineno,
                    f"host-sync {what!r} in a kernel module: {hint} — "
                    f"kernel math must stay on device; host setup belongs "
                    f"in __init__",
                    qualname_of(self._stack)))
        self.generic_visit(node)


# ---- static: use-after-donation ---------------------------------------------

def _donating_call(call: ast.Call) -> str | None:
    """The donated (first) argument's dotted name when ``call`` invokes a
    donating kernel: ``self.kernels.evict(pool, ...)`` or the bucketed
    ``self._step_fn(batch)(pool, packed)`` shape."""
    func = call.func
    name = dotted_name(func)
    leaf = name.rsplit(".", 1)[-1] if name else ""
    is_donating = leaf in DONATING_KERNELS and "." in name
    if not is_donating and isinstance(func, ast.Call):
        inner = dotted_name(func.func)
        if inner.rsplit(".", 1)[-1] == "_step_fn":
            is_donating = True
    if not is_donating or not call.args:
        return None
    donated = dotted_name(call.args[0])
    return donated or None


class _DonationAnalysis(df.Analysis):
    """State: dotted name → "donated".  A read after donation (before a
    rebind from the call result) is the finding."""

    def __init__(self, sf: SourceFile, qual: str):
        self.sf = sf
        self.qual = qual
        self.findings: list[Finding] = []
        self.report = False
        self._seen: set[tuple] = set()

    def join(self, a, b):
        return a if a == b else "donated"  # donated-on-some-path dominates

    def _stmt_reads(self, stmt: ast.AST) -> set[str]:
        out: set[str] = set()
        targets: set[int] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for sub in ast.walk(t):
                    targets.add(id(sub))
        for expr in _header_exprs(stmt):
            for sub in ast.walk(expr):
                if id(sub) in targets:
                    continue
                name = dotted_name(sub)
                if name:
                    out.add(name)
        return out

    def transfer(self, node: df.Node, state, cfg):
        stmt = node.stmt
        if stmt is None:
            return state
        # Reads of donated buffers (the assignment's own RHS counts; its
        # targets do not).
        reads = self._stmt_reads(stmt)
        for name in list(state):
            if state[name] != "donated":
                continue
            if any(r == name or r.startswith(name + ".")
                   or r.startswith(name + "[") for r in reads):
                if self.report:
                    key = ("uad", name, stmt.lineno)
                    if key not in self._seen:
                        self._seen.add(key)
                        self.findings.append(Finding(
                            RULE, self.sf.path, stmt.lineno,
                            f"use of {name!r} after it was DONATED to a "
                            f"kernel call: the buffer is dead (donate_"
                            f"argnums=0) — rebind it from the call's "
                            f"result first",
                            self.qual))
        # Donations + rebinds.
        donated_here: list[str] = []
        for expr in _header_exprs(stmt):
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Call):
                    d = _donating_call(sub)
                    if d is not None:
                        donated_here.append(d)
        rebound: set[str] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                for e in elts:
                    name = dotted_name(e)
                    if name:
                        rebound.add(name)
        for d in donated_here:
            if d not in rebound:
                state[d] = "donated"
        for r in rebound:
            state.pop(r, None)
        return state


_header_exprs = df.header_exprs


def check_static(sources: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in sources:
        if sf.path in KERNEL_MODULES:
            v = _HostSyncScanner(sf)
            v.visit(sf.tree)
            findings.extend(v.findings)
        if sf.path.startswith(ENGINE_PREFIX):
            for cls, fn in _iter_functions(sf.tree):
                uses = any(_donating_call(c) for n in ast.walk(fn)
                           for c in ([n] if isinstance(n, ast.Call)
                                     else []))
                if not uses:
                    continue
                qual = f"{cls}.{fn.name}" if cls else fn.name
                cfg = df.CFG(fn)
                analysis = _DonationAnalysis(sf, qual)
                df.solve_and_report(cfg, analysis)
                findings.extend(analysis.findings)
    return findings


_iter_functions = df.iter_functions


# ---- trace half -------------------------------------------------------------

def _walk_jaxpr(jaxpr, visit: Callable[[Any], None]) -> None:
    for eqn in jaxpr.eqns:
        visit(eqn)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _walk_jaxpr(sub, visit)


def _sub_jaxprs(v):
    if hasattr(v, "jaxpr"):        # ClosedJaxpr
        yield v.jaxpr
    elif hasattr(v, "eqns"):       # raw Jaxpr
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def _trace(fn, *args):
    import jax

    raw = getattr(fn, "__wrapped__", fn)
    return jax.make_jaxpr(lambda *a: raw(*a))(*args)


def _check_callbacks(closed, family: str, ctx: str,
                     findings: list[Finding]) -> None:
    hits: list[str] = []

    def visit(eqn):
        name = eqn.primitive.name
        if any(p in name for p in _CALLBACK_PRIMS):
            hits.append(name)

    _walk_jaxpr(closed.jaxpr, visit)
    for name in sorted(set(hits)):
        findings.append(Finding(
            RULE, ctx, 0,
            f"host callback primitive {name!r} inside jitted kernel "
            f"{family}: a host round trip per window on the hot step",
            family))


def _pool_dtypes(tree) -> dict[str, Any]:
    return {k: v.dtype for k, v in tree.items()}


def _check_pool_preserved(fn, family: str, ctx: str, pool, args,
                          findings: list[Finding],
                          out_pool=None) -> "dict[str, Any] | None":
    """eval_shape the step; the output pool's dtypes must equal the input
    pool's.  Returns the output pool dtype map (for cross-family checks),
    or None when tracing failed (reported)."""
    import jax

    try:
        out = jax.eval_shape(fn, pool, *args)
    except Exception as e:
        findings.append(Finding(
            RULE, ctx, 0,
            f"could not trace {family}: {type(e).__name__}: {e}", family))
        return None
    pool_out = out[0] if isinstance(out, tuple) else out
    want = _pool_dtypes(pool)
    got = _pool_dtypes(pool_out)
    for k in sorted(want):
        if k in got and got[k] != want[k]:
            findings.append(Finding(
                RULE, ctx, 0,
                f"dtype drift in {family}: pool field {k!r} enters "
                f"{want[k]} and leaves {got[k]} — an upcast breaks "
                f"donation reuse and doubles HBM",
                family))
    return got


# ---- padded-lane taint ------------------------------------------------------

_CMP_PRIMS = {"lt", "le", "gt", "ge", "eq", "ne"}
_BOOL_PRIMS = {"and", "or", "not", "xor"}
#: Index-consuming prims: taint flows from the OPERAND, never the indices
#: (a clipped index read returns a real pool value).
_GATHER_PRIMS = {"gather", "dynamic_slice", "take", "argmax", "argmin"}


def check_padded_lanes(fn, args, sentinel_arg: int, family: str,
                       ctx: str = "matchmaking_tpu/engine/kernels.py",
                       ) -> list[Finding]:
    """Forward sentinel taint over ``fn``'s jaxpr.  ``sentinel_arg`` is the
    index (into the FLATTENED invars) of the array carrying masked-lane
    sentinels.  A function output still sentinel-tainted means masked
    lanes reach an accumulator without a select-style sanitizer —
    ``0 × inf = NaN`` contamination (the QualityAccumKernel shape)."""
    import jax

    findings: list[Finding] = []
    try:
        closed = _trace(fn, *args)
    except Exception as e:
        findings.append(Finding(
            RULE, ctx, 0,
            f"could not trace {family} for the padded-lane audit: "
            f"{type(e).__name__}: {e}", family))
        return findings
    jaxpr = closed.jaxpr
    flat_in = jaxpr.invars
    taint: dict[int, set[str]] = {}

    def t(v) -> set[str]:
        return taint.get(id(v), set())

    if sentinel_arg >= len(flat_in):
        return findings
    taint[id(flat_in[sentinel_arg])] = {"sentinel"}

    def run(jx) -> None:
        for eqn in jx.eqns:
            name = eqn.primitive.name
            ins = [t(v) for v in eqn.invars]
            flat = set().union(*ins) if ins else set()
            if name in _CMP_PRIMS:
                out: set[str] = {"mask"} if "sentinel" in flat else set()
            elif name in _BOOL_PRIMS:
                out = flat & {"mask"}
            elif name == "select_n":
                pred = ins[0] if ins else set()
                cases = ins[1:]
                if "mask" in pred:
                    # Validity-gated select: sanitizes when any branch is
                    # clean (the masked lanes take the clean branch).
                    out = ({"sentinel"}
                           if cases and all("sentinel" in c for c in cases)
                           else set())
                else:
                    out = {f for c in cases for f in c}
            elif name in _GATHER_PRIMS:
                out = ins[0] if ins else set()
            elif any(p in name for p in ("pjit", "scan", "while", "cond",
                                         "custom_jvp", "custom_vjp",
                                         "remat", "closed_call")):
                # Sub-jaxpr: map argument taints onto the inner invars,
                # run, and map back.
                subs = [s for v in eqn.params.values()
                        for s in _sub_jaxprs(v)]
                if subs:
                    inner = subs[0]
                    n = min(len(inner.invars), len(eqn.invars))
                    for iv, ov in zip(inner.invars[-n:], eqn.invars[-n:]):
                        if t(ov):
                            taint[id(iv)] = set(t(ov))
                    run(inner)
                    m = min(len(inner.outvars), len(eqn.outvars))
                    for iv, ov in zip(inner.outvars[:m], eqn.outvars[:m]):
                        taint[id(ov)] = set(t(iv))
                    continue
                out = flat
            else:
                out = flat
            for v in eqn.outvars:
                taint[id(v)] = set(out)

    run(jaxpr)
    for i, v in enumerate(jaxpr.outvars):
        if "sentinel" in t(v):
            findings.append(Finding(
                RULE, ctx, 0,
                f"padded-lane contamination in {family}: output #{i} is "
                f"reachable from the masked-lane sentinel input without a "
                f"validity select — 0 × inf = NaN poisons the "
                f"accumulator; sanitize with jnp.where(valid, x, 0) "
                f"BEFORE any masked arithmetic",
                family))
    return findings


# ---- ppermute ring audit ----------------------------------------------------

def _check_ring(closed, n_shards: int, family: str, ctx: str,
                findings: list[Finding]) -> None:
    perms: list[tuple] = []

    def visit(eqn):
        if eqn.primitive.name == "ppermute":
            perms.append(tuple(sorted(map(tuple, eqn.params["perm"]))))

    _walk_jaxpr(closed.jaxpr, visit)
    if not perms:
        findings.append(Finding(
            RULE, ctx, 0,
            f"{family}: ring=True but no ppermute in the trace — the ring "
            f"exchange silently fell back to something else", family))
        return
    if len(set(perms)) > 1:
        findings.append(Finding(
            RULE, ctx, 0,
            f"{family}: ppermute hops use INCONSISTENT permutations "
            f"({len(set(perms))} distinct) — every ring hop must rotate "
            f"the same direction or shards merge stale candidates",
            family))
    perm = perms[0]
    srcs = [s for s, _ in perm]
    dsts = [d for _, d in perm]
    ok = (sorted(srcs) == list(range(n_shards))
          and sorted(dsts) == list(range(n_shards)))
    if ok:
        # Single cycle covering the axis: follow the permutation.
        nxt = dict(perm)
        seen = set()
        cur = 0
        for _ in range(n_shards):
            seen.add(cur)
            cur = nxt[cur]
        ok = len(seen) == n_shards and cur == 0
    if not ok:
        findings.append(Finding(
            RULE, ctx, 0,
            f"{family}: ppermute permutation {perm} is not a single "
            f"{n_shards}-cycle over the mesh axis — some shard's "
            f"candidates never reach every peer", family))


# ---- the audit driver -------------------------------------------------------

def check_dynamic() -> list[Finding]:
    """Audit every kernel family under canonical configs.  Trace-only: no
    kernel executes, no TPU backend is touched (jax stays on whatever
    platform the host process configured — the CLI pins CPU)."""
    findings: list[Finding] = []
    import jax

    from matchmaking_tpu.engine.kernels import (
        QualityAccumKernel,
        kernel_set,
    )

    ctx = "matchmaking_tpu/engine/kernels.py"
    family_pool_dtypes: dict[str, dict] = {}
    for label, kwargs in (
        ("1v1", dict(glicko2=False, widen_per_sec=5.0)),
        ("1v1-glicko2", dict(glicko2=True, widen_per_sec=0.0)),
    ):
        ks = kernel_set(capacity=64, top_k=4, pool_block=32,
                        max_threshold=400.0, pair_rounds=4, **kwargs)
        pool = _canonical_pool(ks, 0)
        packed = _canonical_packed(ks, 16, 0)
        for name in ("search_step_packed", "search_step_packed_nofilter",
                     "search_step_packed_rescan", "admit_packed"):
            fn = getattr(ks, name, None)
            if fn is None:
                continue
            family = f"kernels.{label}.{name}"
            try:
                closed = _trace(fn, pool, packed)
            except Exception as e:
                findings.append(Finding(
                    RULE, ctx, 0,
                    f"could not trace {family}: {type(e).__name__}: {e}",
                    family))
                continue
            _check_callbacks(closed, family, ctx, findings)
            got = _check_pool_preserved(fn, family, ctx, pool, (packed,),
                                        findings)
            if got is not None and name == "search_step_packed":
                family_pool_dtypes[label] = got

    # Team family (object windows): same pool layout, own step shape.
    from matchmaking_tpu.engine.teams import team_kernel_set

    tks = team_kernel_set(capacity=64, team_size=2, widen_per_sec=5.0,
                          max_threshold=400.0, max_matches=8, rounds=4)
    tctx = "matchmaking_tpu/engine/teams.py"
    pool = _canonical_pool(tks, 0)
    packed = _canonical_packed(tks, 16, 0)
    try:
        closed = _trace(tks.search_step_packed, pool, packed)
        _check_callbacks(closed, "teams.search_step_packed", tctx, findings)
        got = _check_pool_preserved(tks.search_step_packed,
                                    "teams.search_step_packed", tctx, pool,
                                    (packed,), findings)
        if got is not None:
            family_pool_dtypes["team"] = got
    except Exception as e:
        findings.append(Finding(
            RULE, tctx, 0,
            f"could not trace teams.search_step_packed: "
            f"{type(e).__name__}: {e}", "teams.search_step_packed"))

    # Role family.
    from matchmaking_tpu.engine.role_kernels import role_kernel_set

    rks = role_kernel_set(capacity=32, team_size=2,
                          role_slots=("tank", "dps"), widen_per_sec=5.0,
                          max_threshold=400.0, max_matches=8, rounds=4)
    rctx = "matchmaking_tpu/engine/role_kernels.py"
    pool = _canonical_pool(rks, 0)
    packed = _canonical_packed(rks, 16, 0)
    fn = getattr(rks, "search_step_packed", None)
    if fn is not None:
        try:
            closed = _trace(fn, pool, packed)
            _check_callbacks(closed, "role_kernels.search_step_packed",
                             rctx, findings)
            got = _check_pool_preserved(fn, "role_kernels.search_step_packed",
                                        rctx, pool, (packed,), findings)
            if got is not None:
                family_pool_dtypes["role"] = got
        except Exception as e:
            findings.append(Finding(
                RULE, rctx, 0,
                f"could not trace role_kernels.search_step_packed: "
                f"{type(e).__name__}: {e}", "role_kernels.search_step_packed"))

    # Cross-family drift on the shared pool fields.
    labels = sorted(family_pool_dtypes)
    for i, a in enumerate(labels):
        for b in labels[i + 1:]:
            da, dtb = family_pool_dtypes[a], family_pool_dtypes[b]
            for k in sorted(set(da) & set(dtb)):
                if da[k] != dtb[k]:
                    findings.append(Finding(
                        RULE, ctx, 0,
                        f"dtype drift BETWEEN kernel families: pool field "
                        f"{k!r} is {da[k]} in {a} but {dtb[k]} in {b} — "
                        f"engine swaps (breaker demotion, elastic "
                        f"placement) would reinterpret the checkpoint",
                        f"{a}~{b}"))

    # Padded-lane contamination: the QualityAccumKernel shape.
    import jax.numpy as jnp
    import numpy as np

    from matchmaking_tpu.engine.quality import QualitySpec

    spec = QualitySpec()
    q = QualityAccumKernel(
        capacity=64, widen_per_sec=5.0, max_threshold=400.0,
        rating_edges=spec.rating_edges, n_quality=spec.n_quality,
        wait_edges=spec.wait_edges)
    state = q.init_state()
    b = 16
    rating = jnp.zeros(64, jnp.float32)
    enq = jnp.zeros(64, jnp.float32)
    thr = jnp.zeros(64, jnp.float32)
    out = jnp.zeros((3, b), jnp.float32)
    now = jnp.float32(1.0)
    n_state = len(jax.tree_util.tree_leaves(state))
    findings.extend(check_padded_lanes(
        q.accum, (state, rating, enq, thr, out, now),
        sentinel_arg=n_state + 3, family="QualityAccumKernel.accum"))

    # Sharded ring audit (needs a multi-device mesh; the pytest CPU mesh
    # has 8 virtual devices — a single-device CLI run skips, silently:
    # absence of devices is an environment fact, not a finding).
    n_dev = len(jax.devices())
    if n_dev >= 2:
        from matchmaking_tpu.engine.sharded import sharded_kernel_set

        n = 4 if n_dev >= 4 else 2
        sctx = "matchmaking_tpu/engine/sharded.py"
        try:
            sks = sharded_kernel_set(
                capacity=64, top_k=4, pool_block=16, glicko2=False,
                widen_per_sec=5.0, max_threshold=400.0, n_shards=n,
                ring=True)
            pool = _canonical_pool(sks, 0)
            packed = _canonical_packed(sks, 16, 0)
            closed = _trace(sks.search_step_packed, pool, packed)
            _check_ring(closed, n, "sharded.search_step_packed(ring)",
                        sctx, findings)
            _check_callbacks(closed, "sharded.search_step_packed", sctx,
                             findings)
            _check_pool_preserved(sks.search_step_packed,
                                  "sharded.search_step_packed", sctx,
                                  pool, (packed,), findings)
        except Exception as e:
            findings.append(Finding(
                RULE, sctx, 0,
                f"could not trace the sharded ring family: "
                f"{type(e).__name__}: {e}", "sharded.ring"))
    return findings


def check(sources: list[SourceFile], dynamic: bool = True) -> list[Finding]:
    findings = check_static(sources)
    if dynamic:
        findings.extend(check_dynamic())
    return findings
