"""Request-lifecycle flight recorder (SURVEY.md §5 per-stage tracing).

The BASELINE north star asserts a p99; this module is what *explains* one.
Three pieces, all bounded-memory and stdlib-only:

- ``TraceContext`` — a lightweight per-delivery trace: an id plus an
  append-only list of ``(stage, wall_clock)`` marks. Stamped at broker
  publish (the "enqueue" mark) and carried on the ``Delivery`` through
  middleware → dedup → batcher → engine window dispatch → settle/publish.
  Marks survive redelivery (the same Delivery object is requeued), and a
  chaos duplicate gets its OWN context stamped at the same publish — a
  trace is the biography of one delivery attempt stream, monotone by
  construction (append order is time order).
- ``FlightRecorder`` — per-queue bounded ring of completed traces plus a
  separate ring of *slow exemplars*: any trace whose enqueue→publish span
  exceeds the configured threshold keeps its full stage breakdown. On
  completion, every adjacent mark pair feeds the shared per-stage latency
  histograms (utils/metrics.py) — the true-histogram replacement for the
  averages-only ``span_report``.
- ``EventLog`` — one bounded ring of lifecycle events (breaker trips,
  probes, delegations, re-promotions, revives, chaos faults, partitions,
  dead-letters) that were previously only visible as scattered counters.
  The ``/debug/events`` surface.

Stage vocabulary (each stage's duration = its mark minus the previous
mark): enqueue → consume → middleware → batch → flush → dispatch → h2d →
device_step → readback_seal → collect → publish, with off-nominal marks
interleaved where they happen (chaos_drop, dedup_replay, oracle_step,
reject). Window-level marks (dispatch..collect) are recorded once per
engine window and merged into every member trace at settle time, so
histogram counts for those stages are per-request attributions.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Any, Iterable

#: Marks recorded once per engine window and merged into member traces.
WINDOW_STAGES = ("dispatch", "h2d", "device_step", "readback_seal",
                 "collect", "oracle_step")

_trace_seq = itertools.count(1)


class TraceContext:
    """One delivery's lifecycle marks. Cheap by design (``__slots__``, one
    list) — it is allocated on EVERY broker publish."""

    __slots__ = ("trace_id", "queue", "correlation_id", "player_id",
                 "redelivered", "status", "tier", "marks", "quality",
                 "waited_s")

    def __init__(self, queue: str, correlation_id: str = "",
                 redelivered: bool = False, t: float | None = None):
        self.trace_id = f"{queue}#{next(_trace_seq)}"
        self.queue = queue
        self.correlation_id = correlation_id
        self.player_id = ""
        self.redelivered = redelivered
        self.status = ""  # set at settle: matched/queued/rejected/...
        #: QoS priority tier (service/overload.py; 0 = untiered default),
        #: stamped at admission so attribution can split per tier.
        self.tier = 0
        #: Outcome values stamped at publish for MATCHED traces (ISSUE 8):
        #: the match's quality scalar and the engine-observed wait-at-match
        #: (dispatch − enqueue, seconds). -1.0 = not matched / not stamped
        #: — lets the quality reconciliation soak recompute histograms
        #: from settled traces.
        self.quality = -1.0
        self.waited_s = -1.0
        self.marks: list[tuple[str, float]] = [
            ("enqueue", time.time() if t is None else t)]

    def mark(self, stage: str, t: float | None = None) -> None:
        self.marks.append((stage, time.time() if t is None else t))

    def extend(self, marks: Iterable[tuple[str, float]]) -> None:
        self.marks.extend(marks)

    @property
    def total_s(self) -> float:
        return self.marks[-1][1] - self.marks[0][1]

    def to_dict(self) -> dict[str, Any]:
        t0 = self.marks[0][1]
        return {
            "trace_id": self.trace_id,
            "queue": self.queue,
            "player_id": self.player_id,
            "correlation_id": self.correlation_id,
            "redelivered": self.redelivered,
            "status": self.status,
            "tier": self.tier,
            **({"quality": round(self.quality, 6),
                "waited_ms": round(self.waited_s * 1e3, 3)}
               if self.quality >= 0.0 else {}),
            "enqueue_t": t0,
            "total_ms": round(self.total_s * 1e3, 3),
            #: absolute wall-clock marks (monotone non-decreasing)
            "marks": [(name, t) for name, t in self.marks],
            #: per-stage breakdown: duration attributed to the LATER mark
            "stages_ms": {
                f"{i}:{name}": round((t - self.marks[i - 1][1]) * 1e3, 3)
                for i, (name, t) in enumerate(self.marks) if i
            },
        }


class EventLog:
    """Bounded ring of lifecycle events — the single place trips, probes,
    delegations, re-promotions, revives and chaos faults become a readable
    timeline instead of counter deltas. Appended from the event loop AND
    engine worker threads (delegation events fire inside to_thread).

    Since ISSUE 18 every append is stamped onto the app's causal
    EventSpine (utils/forensics.py): rows carry the process-wide monotone
    ``seq`` + ``mono_ns`` pair and a ``component`` tag, plus optional
    ``refs`` linking causal neighbors (epoch, decision id, WAL range).
    ``snapshot()`` orders by SEQ, not wall clock — two events in the same
    millisecond can no longer render out of causal order, and a worker
    thread that drew its seq but lost the append race to the ring no
    longer appears late."""

    def __init__(self, maxlen: int = 512, spine=None):
        self._events: deque[dict[str, Any]] = deque(maxlen=max(1, maxlen))
        if spine is None:
            # Standalone EventLog (tests, subsystems constructed without
            # an app): own a private spine so rows are shaped identically.
            from matchmaking_tpu.utils.forensics import EventSpine

            spine = EventSpine(ring=max(1, maxlen))
        self.spine = spine

    def append(self, kind: str, queue: str = "", detail: str = "",
               component: str = "",
               refs: "dict[str, Any] | None" = None) -> dict[str, Any]:
        ev = self.spine.stamp(kind, queue, detail, component=component,
                              refs=refs)
        self._events.append(ev)
        return ev

    def __len__(self) -> int:
        return len(self._events)

    def snapshot(self, queue: str | None = None,
                 limit: int = 0) -> list[dict[str, Any]]:
        # tuple() first: worker threads append concurrently, and iterating
        # a live deque across their mutations raises RuntimeError.
        rows = [
            {"seq": ev["seq"], "t": ev["wall"], "mono_ns": ev["mono_ns"],
             "component": ev["component"], "kind": ev["kind"],
             "queue": ev["queue"], "detail": ev["detail"],
             "refs": ev["refs"]}
            for ev in tuple(self._events)
            if queue is None or ev["queue"] == queue
        ]
        # Causal order is the SEQ order: ring append order can diverge
        # when a worker thread is preempted between its seq draw and the
        # stamp landing (the old wall-clock-only sort had the same hole
        # one level up).
        rows.sort(key=lambda r: r["seq"])
        return rows[-limit:] if limit else rows


class FlightRecorder:
    """Per-queue rings of completed traces + slow exemplars; feeds the
    per-stage histograms on every completion."""

    def __init__(self, metrics, ring: int = 256, slow_ring: int = 64,
                 slow_threshold_s: float = 0.25):
        self._metrics = metrics
        self._ring = max(1, ring)
        self._slow_ring = max(1, slow_ring)
        self.slow_threshold_s = slow_threshold_s
        self._recent: dict[str, deque[TraceContext]] = {}
        self._slow: dict[str, deque[TraceContext]] = {}
        #: Critical-path attribution sink (service/attribution.Attribution),
        #: attached by the app: every settled trace is decomposed into
        #: work-vs-wait categories alongside the stage histograms. None =
        #: attribution off.
        self.attribution = None

    def complete(self, trace: TraceContext) -> None:
        """Settle one trace: derive per-stage durations from adjacent mark
        pairs into the shared histograms, record it in the recent ring, and
        keep it as a slow exemplar when the enqueue→publish span exceeds
        the threshold."""
        q = trace.queue
        marks = trace.marks
        if self._metrics is not None:
            observe = self._metrics.observe_stage
            prev_t = marks[0][1]
            for name, t in marks[1:]:
                observe(q, name, max(0.0, t - prev_t))
                prev_t = t
            observe(q, "total", max(0.0, marks[-1][1] - marks[0][1]))
        if self.attribution is not None:
            self.attribution.observe(trace)
        ring = self._recent.get(q)
        if ring is None:
            ring = self._recent[q] = deque(maxlen=self._ring)
        ring.append(trace)
        if trace.total_s >= self.slow_threshold_s:
            slow = self._slow.get(q)
            if slow is None:
                slow = self._slow[q] = deque(maxlen=self._slow_ring)
            slow.append(trace)

    def percentile_exemplar(self, queue: str,
                            p: float = 99.0) -> TraceContext | None:
        """The settled trace sitting at the p-th percentile of total span
        among the RECENT ring (nearest rank) — the exemplar whose
        decomposition /debug/attribution quotes: unlike a histogram-side
        p99, its per-gap durations sum to its span exactly."""
        ring = self._recent.get(queue)
        if not ring:
            return None
        by_total = sorted(ring, key=lambda t: t.total_s)
        import math

        k = min(len(by_total) - 1,
                max(0, math.ceil(p / 100.0 * len(by_total)) - 1))
        return by_total[k]

    def get(self, trace_id: str) -> TraceContext | None:
        for rings in (self._slow, self._recent):
            for ring in rings.values():
                for tr in ring:
                    if tr.trace_id == trace_id:
                        return tr
        return None

    def snapshot(self, queue: str | None = None,
                 limit: int = 32) -> dict[str, Any]:
        queues = ([queue] if queue is not None
                  else sorted(set(self._recent) | set(self._slow)))
        out: dict[str, Any] = {}
        for q in queues:
            recent = list(self._recent.get(q, ()))[-limit:]
            slow = list(self._slow.get(q, ()))[-limit:]
            out[q] = {
                "recent": [t.to_dict() for t in recent],
                "slow": [t.to_dict() for t in slow],
            }
        return {"slow_threshold_ms": self.slow_threshold_s * 1e3,
                "queues": out}
