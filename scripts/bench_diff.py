#!/usr/bin/env python
"""Gate a fresh BENCH json against the committed trajectory.

The BENCH_r*.json trajectory was append-only: a PR could halve throughput
or quality and nothing would fail until a human read the numbers. This
tool compares a fresh bench result against the newest committed round and
exits nonzero on any metric regressing more than ``--threshold`` (10% by
default):

    python scripts/bench_diff.py /tmp/BENCH_fresh.json
    python scripts/bench_diff.py fresh.json --baseline BENCH_r04.json
    MM_BENCH_JSON=/tmp/BENCH_fresh.json scripts/check.sh   # the CI hook

Input formats (both sides): a raw bench result object (the final JSON line
``bench.py`` prints), a JSON-lines file whose last parseable object wins,
or a driver artifact wrapping the result under ``"parsed"`` (the committed
BENCH_r*.json shape). Metrics present on only one side are skipped — the
gate compares what both rounds measured, so adding a new bench phase never
fails old baselines.

Compared metrics (direction-aware):
    higher is better:  value (headline matches/s), e2e_rate_req_s
                       (ISSUE 9: the service-path headline the 8x-gap work
                       moves), e2e_matched_per_s, e2e_knee_req_s,
                       e2e_slo_attainment, frontier quality_mean,
                       spec_hit_rate (ISSUE 16)
    lower is better:   p99_ms, e2e_p99_ms, frontier wait_at_match_ms_p99,
                       frontier quality_disparity, the placement-soak
                       rows (ISSUE 11): placement_blackout_ms_max/mean,
                       placement_lost, placement_dup, the crash-soak
                       rows (ISSUE 15): crash_lost, crash_dup,
                       crash_rto_ms_max/mean, crash_failover_blackout_ms,
                       journal_write_amplification,
                       crash_journal_overhead_frac, the speculation
                       A/B rows (ISSUE 16): spec_turnaround_ms_p50/p99,
                       spec_wasted_step_fraction, and the failover-soak
                       rows (ISSUE 17): failover_lost, failover_dup,
                       failover_lost_over_bound, failover_rto_ms(_mean),
                       replication_lag_ms_p99 (lost/dup/over-bound under
                       the zero-baseline rule), the model-checker
                       rows (ISSUE 19): modelcheck_violations (zero
                       baseline — any counterexample regresses) with
                       modelcheck_states_explored higher-is-better
                       (coverage at the committed scope), and the
                       cross-process socket failover rows (ISSUE 20):
                       socket_failover_lost/dup/lost_over_bound,
                       heartbeat_false_positive_count, and
                       socket_fenced_probe_failures under the
                       zero-baseline rule, with
                       socket_failover_rto_ms(_mean) and
                       socket_link_reconnects lower-is-better
Frontier rows (``e2e_frontier``, ISSUE 8; the speculation-axis twin
``e2e_frontier_spec``, ISSUE 16) are matched by threshold.
Scenario-matrix cells (``scenario_matrix``, ISSUE 13) are matched by
scenario name — slo_attainment / quality up, admitted_p99_ms / expired
down — and cells carrying an ``abort_reason`` are skipped on either side,
exactly like aborted rounds. Pool-scale rows (``pool_scale``, ISSUE 14)
are matched by synthetic pool size — matches_per_sec up, p99_ms and
``formation_touched_frac`` down (the sub-O(P) formation headline; a
rising fraction means formation is sliding back toward the flat scan).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

#: metric name → True when HIGHER is better.
TOP_LEVEL_METRICS: dict[str, bool] = {
    "value": True,
    "e2e_rate_req_s": True,
    "e2e_matched_per_s": True,
    "e2e_knee_req_s": True,
    "e2e_slo_attainment": True,
    "p99_ms": False,
    "e2e_p99_ms": False,
    # Consume/decode ingest share of the settled span (ISSUE 12): the
    # broker-consume + wire-decode work fraction the consume_batch seam
    # shrinks — regressing it re-opens the per-delivery ingress wall.
    "e2e_consume_share": False,
    # Elastic placement soak (ISSUE 11, bench.py --placement-soak):
    # migration blackout and delivery accounting regress downward only.
    # lost/dup have a zero baseline on a healthy run, so ANY nonzero
    # fresh value beyond the threshold regresses (see the base==0 rule).
    "placement_blackout_ms_max": False,
    "placement_blackout_ms_mean": False,
    "placement_lost": False,
    "placement_dup": False,
    # Hierarchical bucketed formation (ISSUE 14): the fraction of the
    # pool each window lane's formation scored — the sub-O(P) headline.
    # Direction-aware DOWN: a rising fraction means formation is sliding
    # back toward the flat O(P) scan (spans too narrow for the live
    # distribution → dense fallbacks).
    "formation_touched_frac": False,
    # Crash-restart soak (ISSUE 15, bench.py --crash-soak): recovery
    # accounting regresses downward only. lost/dup have a zero baseline
    # on a healthy soak, so ANY nonzero fresh value beyond the threshold
    # regresses (the base==0 rule); the RTO, failover blackout, journal
    # write amplification, and the fsync=window steady-state append
    # overhead are all lower-is-better latencies/costs.
    "crash_lost": False,
    "crash_dup": False,
    "crash_rto_ms_max": False,
    "crash_rto_ms_mean": False,
    "crash_failover_blackout_ms": False,
    "journal_write_amplification": False,
    "crash_journal_overhead_frac": False,
    # Hot-standby failover soak (ISSUE 17, bench.py --failover-soak):
    # cross-host takeover accounting regresses downward only. lost/dup
    # (and the over-bound excess — players lost BEYOND the unacked-tail
    # bound measured at kill time, zero on any correct run) have a zero
    # baseline, so ANY nonzero fresh value beyond the threshold
    # regresses (the base==0 rule); the takeover RTO and the replication
    # ack-lag p99 are lower-is-better latencies. A run without the soak
    # leaves the keys absent and they are skipped per-metric.
    "failover_lost": False,
    "failover_dup": False,
    "failover_lost_over_bound": False,
    "failover_rto_ms": False,
    "failover_rto_ms_mean": False,
    "replication_lag_ms_p99": False,
    # Speculative formation A/B (ISSUE 16, bench.py --spec-ab): the
    # spec-on leg's turnaround (engine-observed wait-at-match) regresses
    # upward, the hit rate downward, the wasted-step fraction (discarded
    # speculative device steps — the overlap price) upward. A chip-less
    # abort leaves these keys absent and they are skipped per-metric,
    # like every other one-sided column.
    "spec_turnaround_ms_p50": False,
    "spec_turnaround_ms_p99": False,
    "spec_hit_rate": True,
    "spec_wasted_step_fraction": False,
    # Small-scope model checker (ISSUE 19, bench.py --modelcheck):
    # states_explored is coverage — a same-scope run that visits fewer
    # unique states means the world's digest collapsed or an action was
    # lost, both silent coverage regressions. violations has a zero
    # baseline on the real protocol, so ANY nonzero fresh value beyond
    # the threshold regresses (the base==0 rule) — a violation count of
    # 1 is a minimized counterexample, not a flaky latency. A run
    # without the phase leaves the keys absent and they are skipped
    # per-metric.
    "modelcheck_states_explored": True,
    "modelcheck_violations": False,
    # Cross-process socket failover soak (ISSUE 20, bench.py
    # --failover-soak --transport=socket): the PR 17 invariants gated
    # OVER THE WIRE. lost/dup/over-bound keep the zero-baseline rule —
    # so do heartbeat_false_positive_count (a liveness verdict that
    # fired on a healthy link means the deadline model is wrong, not
    # slow) and socket_fenced_probe_failures (a fence seam that leaked
    # at the SIGKILLed-and-superseded ex-primary is split-brain, never a
    # latency). The takeover RTO over real sockets is a lower-is-better
    # latency; socket_link_reconnects is lower-is-better churn (the
    # scripted reset accounts for the baseline's floor — MORE reconnects
    # at the same script means the transport started tearing healthy
    # connections). A run without the soak leaves the keys absent and
    # they are skipped per-metric.
    "socket_failover_lost": False,
    "socket_failover_dup": False,
    "socket_failover_lost_over_bound": False,
    "socket_failover_rto_ms": False,
    "socket_failover_rto_ms_mean": False,
    "socket_link_reconnects": False,
    "heartbeat_false_positive_count": False,
    "socket_fenced_probe_failures": False,
}

#: Pool-scale sweep rows (ISSUE 14, ``bench.py --pool-scale``), matched
#: by synthetic pool size.
POOL_SCALE_METRICS: dict[str, bool] = {
    "matches_per_sec": True,
    "p99_ms": False,
    "formation_touched_frac": False,
}

FRONTIER_METRICS: dict[str, bool] = {
    "quality_mean": True,
    "wait_at_match_ms_p99": False,
    "quality_disparity": False,
}

#: Scenario-matrix cell metrics (ISSUE 13, ``bench.py --scenario-matrix``):
#: cells are matched by scenario NAME, aborted cells (``abort_reason``
#: set) are skipped on either side — a backend outage in one cell is an
#: environment fact, not a regression.
SCENARIO_METRICS: dict[str, bool] = {
    "slo_attainment": True,
    "admitted_p99_ms": False,
    "expired": False,
}
SCENARIO_QUALITY_METRICS: dict[str, bool] = {
    "quality_mean": True,
    "quality_p10": True,
}


def load_result(path: str) -> dict:
    """One bench result dict from any of the accepted shapes."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        # JSON-lines: last parseable object wins.
        doc = None
        for line in text.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
        if doc is None:
            raise SystemExit(f"{path}: no JSON object found")
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]  # driver artifact (BENCH_r*.json)
    if not isinstance(doc, dict):
        raise SystemExit(f"{path}: expected a JSON object")
    return doc


def abort_reason_of(doc: dict) -> str | None:
    """The round's gate-skipping abort reason (ISSUE 12 satellite — what
    burned BENCH_r05): an ``abort_reason``/``error`` on a round with NO
    usable headline ``value``. A PARTIAL abort (reason recorded but the
    headline measured — e.g. the cpu-fallback's e2e leg failed after the
    comms rows landed) keeps the gate: its present metrics still compare,
    and the missing ones are skipped per-metric anyway."""
    if doc.get("value") is not None:
        return None
    reason = doc.get("abort_reason")
    if isinstance(reason, str) and reason:
        return reason
    err = doc.get("error")
    if isinstance(err, str) and err:
        return err
    return None


def newest_committed_baseline(root: str) -> str | None:
    """The newest BENCH_r*.json whose result carries a usable headline
    ``value`` (r05 recorded a backend outage — value null — and must not
    become the bar)."""
    candidates = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                        reverse=True)
    for path in candidates:
        try:
            row = load_result(path)
        except SystemExit:
            continue
        if row.get("value") is not None:
            return path
    return None


def _compare_one(name: str, base, fresh, higher_better: bool,
                 threshold: float) -> dict | None:
    """None when not comparable; a row dict otherwise (``regressed`` set
    when the fresh value is worse by more than ``threshold``)."""
    if not isinstance(base, (int, float)) or not isinstance(
            fresh, (int, float)):
        return None
    if isinstance(base, bool) or isinstance(fresh, bool):
        return None
    if base == 0:
        # Ratio undefined. For lower-is-better metrics (disparity, p99 of
        # an empty round) a zero baseline is the BEST possible bar — any
        # absolute worsening beyond the threshold regresses (disparity is
        # bounded in [0,1], so the absolute scale is meaningful); a
        # zero-baseline higher-is-better metric can only improve.
        worse_abs = 0.0 if higher_better else fresh
        return {
            "metric": name,
            "baseline": base,
            "fresh": fresh,
            "change": round(float(fresh - base), 4),
            "regressed": worse_abs > threshold,
        }
    change = (fresh - base) / abs(base)
    worse = -change if higher_better else change
    return {
        "metric": name,
        "baseline": base,
        "fresh": fresh,
        "change": round(change, 4),
        "regressed": worse > threshold,
    }


def diff(baseline: dict, fresh: dict,
         threshold: float = 0.10) -> list[dict]:
    """All comparable metric rows between two bench results."""
    rows: list[dict] = []
    for name, higher in TOP_LEVEL_METRICS.items():
        row = _compare_one(name, baseline.get(name), fresh.get(name),
                           higher, threshold)
        if row is not None:
            rows.append(row)
    # Frontier rows matched by threshold value (ISSUE 8); the
    # speculation-axis rows (ISSUE 16, ``e2e_frontier_spec``) gate the
    # same metrics spec-on vs spec-on so the fairness bar travels with
    # the overlap.
    for key in ("e2e_frontier", "e2e_frontier_spec"):
        base_frontier = {r.get("threshold"): r
                         for r in baseline.get(key, [])
                         if isinstance(r, dict)}
        for fr in fresh.get(key, []):
            if not isinstance(fr, dict):
                continue
            br = base_frontier.get(fr.get("threshold"))
            if br is None:
                continue
            for name, higher in FRONTIER_METRICS.items():
                row = _compare_one(
                    f"{key}[thr={fr.get('threshold'):g}].{name}",
                    br.get(name), fr.get(name), higher, threshold)
                if row is not None:
                    rows.append(row)
    # Pool-scale rows matched by synthetic pool size (ISSUE 14).
    base_scale = {r.get("pool"): r for r in baseline.get("pool_scale", [])
                  if isinstance(r, dict)}
    for fr in fresh.get("pool_scale", []):
        if not isinstance(fr, dict):
            continue
        br = base_scale.get(fr.get("pool"))
        if br is None:
            continue
        for name, higher in POOL_SCALE_METRICS.items():
            row = _compare_one(
                f"pool_scale[{fr.get('pool')}].{name}",
                br.get(name), fr.get(name), higher, threshold)
            if row is not None:
                rows.append(row)
    # Scenario-matrix cells matched by scenario name (ISSUE 13); aborted
    # cells on either side are skipped, like aborted rounds.
    base_cells = {c.get("scenario"): c
                  for c in baseline.get("scenario_matrix", [])
                  if isinstance(c, dict) and not c.get("abort_reason")}
    for fc in fresh.get("scenario_matrix", []):
        if not isinstance(fc, dict) or fc.get("abort_reason"):
            continue
        bc = base_cells.get(fc.get("scenario"))
        if bc is None:
            continue
        tag = f"scenario[{fc.get('scenario')}]"
        for name, higher in SCENARIO_METRICS.items():
            row = _compare_one(f"{tag}.{name}", bc.get(name),
                               fc.get(name), higher, threshold)
            if row is not None:
                rows.append(row)
        bq, fq = bc.get("quality") or {}, fc.get("quality") or {}
        for name, higher in SCENARIO_QUALITY_METRICS.items():
            row = _compare_one(f"{tag}.quality.{name}", bq.get(name),
                               fq.get(name), higher, threshold)
            if row is not None:
                rows.append(row)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("fresh", help="fresh BENCH json (bench.py output)")
    ap.add_argument("--baseline", default="",
                    help="committed baseline (default: newest BENCH_r*.json "
                         "with a usable headline value)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative regression tolerance (0.10 = 10%%)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    args = ap.parse_args(argv)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    baseline_path = args.baseline or newest_committed_baseline(root)
    if baseline_path is None:
        print("bench_diff: no committed baseline found — nothing to gate")
        return 0
    baseline = load_result(baseline_path)
    fresh = load_result(args.fresh)
    # Aborted rounds are SKIPPED, not failed (ISSUE 12 satellite): a
    # backend outage is an environment fact, not a regression — the round
    # keeps its partial results and the gate simply declines to compare.
    for side, doc, path in (("fresh", fresh, args.fresh),
                            ("baseline", baseline, baseline_path)):
        reason = abort_reason_of(doc)
        if reason is not None:
            print(f"bench_diff: {side} round {path} aborted "
                  f"({reason}) — skipping the gate")
            return 0
    rows = diff(baseline, fresh, threshold=args.threshold)
    regressions = [r for r in rows if r["regressed"]]
    if args.json:
        print(json.dumps({"baseline": baseline_path, "rows": rows,
                          "regressions": len(regressions)}, indent=1))
    else:
        print(f"baseline: {baseline_path}")
        for r in rows:
            flag = "REGRESSED" if r["regressed"] else "ok"
            print(f"  {r['metric']:<44} {r['baseline']:>12} -> "
                  f"{r['fresh']:>12}  ({r['change']:+.1%})  {flag}")
        if not rows:
            print("  (no comparable metrics — baselines predate this "
                  "bench's phases)")
    if regressions:
        print(f"bench_diff: {len(regressions)} regression(s) beyond "
              f"{args.threshold:.0%}", file=sys.stderr)
        return 1
    print("bench_diff: within tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main())
