"""Seeded chaos-schedule tests (`chaos` marker): deterministic fault
injection end to end.

The crash-storm test is the subsystem's acceptance run: a scripted device
step failing on k consecutive windows trips the per-queue circuit breaker,
matches keep flowing on the host-oracle path with zero invariant violations
and zero lost deliveries, and an exponential-backoff half-open probe
re-promotes the device engine — and because every fault decision is a pure
function of (seed, queue, seq/step index), the whole run replays
bit-identically, asserted by running the scenario twice and comparing
transcripts. All of these are tier-1-safe smokes (seeded schedules, small
pools, single-digit seconds on the 1-core CPU mesh)."""

import asyncio
import json

import pytest

from matchmaking_tpu.config import (
    BatcherConfig,
    ChaosConfig,
    Config,
    EngineConfig,
    QueueConfig,
)
from matchmaking_tpu.service.app import MatchmakingApp
from matchmaking_tpu.service.breaker import CLOSED, OPEN
from matchmaking_tpu.service.broker import Properties

pytestmark = pytest.mark.chaos


async def _drain_replies(app, reply: str) -> list[dict]:
    out = []
    while True:
        d = await app.broker.get(reply, timeout=0.05)
        if d is None:
            return out
        out.append(json.loads(d.body))


def _matched_pairs(replies: list[dict]) -> list[tuple[str, ...]]:
    """Each match reported once per player — collapse to the sorted set of
    player tuples (match_id is a per-process uuid, excluded on purpose)."""
    pairs = {
        tuple(sorted(r["match"]["players"]))
        for r in replies if r["status"] == "matched"
    }
    return sorted(pairs)


async def _crash_storm_run() -> dict:
    """One full crash-storm scenario; returns the run's transcript (every
    field deterministic under the chaos seed)."""
    q = QueueConfig(name="mm.chaos", rating_threshold=100.0,
                    send_queued_ack=False)
    cfg = Config(
        queues=(q,),
        engine=EngineConfig(backend="tpu", pool_capacity=64, pool_block=32,
                            batch_buckets=(32,), pipeline_depth=2,
                            breaker_threshold=3, breaker_window_s=60.0,
                            breaker_probe_initial_s=0.15,
                            breaker_probe_backoff=2.0,
                            breaker_probe_max_s=2.0,
                            health_interval_s=0.05),
        batcher=BatcherConfig(max_batch=32, max_wait_ms=2.0),
        # The storm: the first 3 device SEARCH-step dispatches raise
        # (k = breaker_threshold consecutive windows), and the FIRST
        # half-open probe fails too (pins the backoff doubling).
        chaos=ChaosConfig(seed=1234, queues=(q.name,),
                          fail_step_ranges=((0, 3),), fail_probes=1),
        debug_invariants=True,
    )
    app = MatchmakingApp(cfg)
    reply = "chaos.replies"
    app.broker.declare_queue(q.name)
    app.broker.declare_queue(reply)
    N = 32
    # Publish BEFORE start: the consumer's first drain sees one full burst,
    # so window composition is identical run to run.
    for i in range(N):
        app.broker.publish(q.name, f'{{"id":"p{i}","rating":1500}}'.encode(),
                           Properties(reply_to=reply, correlation_id=f"c{i}"))
    await app.start()
    rt = app.runtime(q.name)
    try:
        # Phase 1 — the storm demotes the queue but matches still flow.
        for _ in range(400):
            await asyncio.sleep(0.05)
            if app.metrics.counters.get("players_matched") >= N:
                break
        assert app.metrics.counters.get("players_matched") == N
        assert app.metrics.counters.get("breaker_trips") == 1
        assert app.metrics.counters.get("engine_crashes") == 3

        # Phase 2 — half-open probes: one scripted failure (backoff
        # doubles), then success re-promotes the device engine.
        for _ in range(400):
            await asyncio.sleep(0.05)
            if rt.breaker.state == CLOSED and rt.breaker.trips == 1:
                break
        assert rt.breaker.state == CLOSED
        assert app.metrics.counters.get("breaker_probe_failures") == 1
        assert app.metrics.counters.get("breaker_closes") == 1
        # Re-promoted for real: the live engine has its device API back.
        assert hasattr(rt.engine, "search_columns_async")

        # Phase 3 — traffic lands on the restored device path (chaos step
        # indices 3+ are past the scripted storm). Ratings come in
        # well-separated pairs (gap ≫ threshold) so each player's ONLY
        # feasible partner is its twin: the kernel's mutual-best pairing
        # resolves all four pairs in this single arrival step — no rescan
        # ticks are configured to re-run formation on leftovers.
        for j, i in enumerate(range(N, N + 8)):
            rating = 1000 + (j // 2) * 300 + (j % 2)
            app.broker.publish(q.name,
                               f'{{"id":"p{i}","rating":{rating}}}'.encode(),
                               Properties(reply_to=reply,
                                          correlation_id=f"c{i}"))
        for _ in range(400):
            await asyncio.sleep(0.05)
            if app.metrics.counters.get("players_matched") >= N + 8:
                break
        assert app.metrics.counters.get("players_matched") == N + 8

        replies = await _drain_replies(app, reply)
        stats = app.broker.stats
        # Zero lost deliveries: every request delivery was eventually acked
        # (crashed windows nack-requeued, never dead-lettered or errored).
        assert stats["dead_lettered"] == 0
        assert stats["consumer_errors"] == 0
        assert app.metrics.counters.get("flush_errors") == 0
        assert app.metrics.counters.get("outcome_errors") == 0
        return {
            "pairs": _matched_pairs(replies),
            "acked": stats["acked"],
            "crashes": app.metrics.counters.get("engine_crashes"),
            "trips": app.metrics.counters.get("breaker_trips"),
            "probes": app.metrics.counters.get("breaker_probes"),
            "probe_failures":
                app.metrics.counters.get("breaker_probe_failures"),
            "degraded_revives":
                app.metrics.counters.get("breaker_degraded_revives"),
            "chaos_steps": app.chaos.engine_hook(q.name).steps,
        }
    finally:
        await app.stop()


def test_chaos_crash_storm_breaker_end_to_end_deterministic(sanitizer):
    """Acceptance run (see module docstring), executed twice with the same
    seed: the transcripts — matched pairs, ack counts, crash/trip/probe
    counts, chaos step indices consumed — must be bit-identical."""
    first = asyncio.run(_crash_storm_run())
    second = asyncio.run(_crash_storm_run())
    # Each player matched exactly once across the whole run.
    assert len(first["pairs"]) == 20  # 16 degraded + 4 post-re-promotion
    assert sorted(p for pair in first["pairs"] for p in pair) == sorted(
        f"p{i}" for i in range(40))
    assert first == second


def test_chaos_breaker_gauges_and_healthz_surface_state(sanitizer):
    """Breaker state is observable while degraded: metrics gauges flip to
    OPEN on the trip and back to CLOSED after re-promotion, and the
    report() payload carries the per-queue snapshot."""
    async def run():
        q = QueueConfig(name="mm.gauge", rating_threshold=100.0,
                        send_queued_ack=False)
        cfg = Config(
            queues=(q,),
            engine=EngineConfig(backend="tpu", pool_capacity=64,
                                pool_block=32, batch_buckets=(16,),
                                pipeline_depth=2, breaker_threshold=2,
                                breaker_window_s=60.0,
                                breaker_probe_initial_s=30.0,
                                health_interval_s=0.05),
            batcher=BatcherConfig(max_batch=16, max_wait_ms=2.0),
            chaos=ChaosConfig(seed=1, queues=(q.name,),
                              fail_step_ranges=((0, 2),)),
        )
        app = MatchmakingApp(cfg)
        reply = "gauge.replies"
        app.broker.declare_queue(q.name)
        app.broker.declare_queue(reply)
        for i in range(4):
            app.broker.publish(q.name,
                               f'{{"id":"g{i}","rating":1500}}'.encode(),
                               Properties(reply_to=reply,
                                          correlation_id=f"c{i}"))
        await app.start()
        rt = app.runtime(q.name)
        try:
            for _ in range(200):
                await asyncio.sleep(0.05)
                if rt.breaker.state == OPEN:
                    break
            assert rt.breaker.state == OPEN  # probe_initial 30 s: stays open
            report = app.metrics.report()
            assert report["gauges"][f"breaker_state[{q.name}]"] == 2
            snap = rt.breaker.snapshot()
            assert snap["trips"] == 1 and snap["state"] == OPEN
            # Live engine is the degraded host oracle.
            assert type(rt.engine).__name__ == "CpuEngine"
            # /healthz surfaces the degradation (handler called directly —
            # no TCP bind needed) and /metrics carries the snapshot.
            from matchmaking_tpu.service.observability import (
                ObservabilityServer,
            )

            srv = ObservabilityServer(app)
            health = json.loads((await srv._healthz(None)).text)
            assert health["status"] == "degraded"
            assert health["degraded_queues"] == [q.name]
            hq = health["queues"][q.name]
            assert hq["engine"] == "CpuEngine" and hq["backend"] == "tpu"
            assert hq["breaker"]["state"] == OPEN
            full = srv._report()
            assert full["breakers"][q.name]["trips"] == 1
            assert full["breakers"][q.name]["time_degraded_s"] > 0
        finally:
            await app.stop()

    asyncio.run(run())


def test_idle_delegated_team_queue_repromotes_on_health_timer(sanitizer):
    """ADVICE round-5 #3 regression: a wildcard-delegated device team queue
    with ``rescan_interval_s=0`` (the team-queue default) and ZERO further
    traffic must re-promote to the device path via the health timer alone —
    before this PR nothing ticked an idle delegated queue."""
    async def run():
        q = QueueConfig(name="mm.team", team_size=2, rating_threshold=200.0,
                        send_queued_ack=False)
        assert q.rescan_interval_s == 0  # the configuration under test
        cfg = Config(
            queues=(q,),
            engine=EngineConfig(backend="tpu", pool_capacity=64,
                                pool_block=32, batch_buckets=(16,),
                                team_max_matches=16,
                                health_interval_s=0.05),
            batcher=BatcherConfig(max_batch=16, max_wait_ms=2.0),
            debug_invariants=True,
        )
        app = MatchmakingApp(cfg)
        reply = "team.replies"
        app.broker.declare_queue(reply)
        await app.start()
        rt = app.runtime(q.name)
        assert rt._rescanner is None  # no rescan heartbeat to lean on
        # Shrink the re-promotion quiet period (instance attr shadows the
        # class constant) so the test completes in seconds.
        rt.engine.TEAM_REPROMOTE_QUIET_S = 0.2
        try:
            # One wildcard (no region/mode) delegates the queue to the host
            # oracle; with three pinned partners the 2v2 match forms and
            # drains the delegate pool immediately.
            bodies = [b'{"id":"w0","rating":1500}'] + [
                (f'{{"id":"t{i}","rating":1500,"region":"eu",'
                 f'"game_mode":"ranked"}}').encode()
                for i in range(3)
            ]
            for i, body in enumerate(bodies):
                app.broker.publish(q.name, body,
                                   Properties(reply_to=reply,
                                              correlation_id=f"c{i}"))
            for _ in range(200):
                await asyncio.sleep(0.05)
                if app.metrics.counters.get("players_matched") >= 4:
                    break
            assert app.metrics.counters.get("players_matched") == 4
            assert rt.engine.counters.get("team_delegated", 0) == 1
            # Idle from here on: no traffic, no rescans, no expiry sweeps.
            # Only the health timer can notice the wildcard pool drained.
            for _ in range(200):
                await asyncio.sleep(0.05)
                if rt.engine.counters.get("team_repromoted", 0) >= 1:
                    break
            assert rt.engine.counters.get("team_repromoted", 0) == 1
            assert rt.engine._team_delegate is None
            assert app.metrics.counters.get("health_repromotions") >= 1
        finally:
            await app.stop()

    asyncio.run(run())


def test_chaos_broker_faults_scripted_and_deterministic(sanitizer):
    """Scripted broker faults on the host backend (no jit — the fastest
    smoke): a first-attempt drop, a redelivery storm, and a partition
    pause/resume, with stats identical across two seeded runs."""
    async def run() -> dict:
        q = QueueConfig(name="mm.b", rating_threshold=100.0,
                        send_queued_ack=False)
        cfg = Config(
            queues=(q,),
            engine=EngineConfig(backend="cpu"),
            batcher=BatcherConfig(max_batch=8, max_wait_ms=1.0),
            # seq 0's first delivery attempt is dropped; publish seq 1 is
            # delivered 1 + 2 times (its storm copies consume seqs 2-3, so
            # the 5th publish carries seq 6 — pause — and the 8th carries
            # seq 9 — resume).
            chaos=ChaosConfig(seed=5, queues=(q.name,), drop_seqs=(0,),
                              dup_seqs=((1, 2),), partitions=((6, 9),),
                              partition_max_s=5.0),
            debug_invariants=True,
        )
        app = MatchmakingApp(cfg)
        reply = "b.replies"
        app.broker.declare_queue(reply)
        await app.start()
        try:
            for i in range(5):  # 5th publish = seq 6: the partition starts
                app.broker.publish(q.name,
                                   f'{{"id":"b{i}","rating":1500}}'.encode(),
                                   Properties(reply_to=reply,
                                              correlation_id=f"c{i}"))
            await asyncio.sleep(0.3)
            assert not app.broker._queues[q.name].gate.is_set()  # paused
            paused_depth = app.broker.queue_depth(q.name)
            for i in range(5, 8):  # 8th publish = seq 9: resume
                app.broker.publish(q.name,
                                   f'{{"id":"b{i}","rating":1500}}'.encode(),
                                   Properties(reply_to=reply,
                                              correlation_id=f"c{i}"))
            for _ in range(100):
                await asyncio.sleep(0.05)
                if app.metrics.counters.get("players_matched") >= 8:
                    break
            assert app.broker._queues[q.name].gate.is_set()  # resumed
            assert app.metrics.counters.get("players_matched") == 8
            s = app.broker.stats
            assert s["dropped"] == 1          # drop_seqs=(0,), first attempt
            assert s["duplicated"] == 2       # the seq-1 storm
            assert s["partitions"] == 1
            assert s["dead_lettered"] == 0
            return {"paused_depth": paused_depth,
                    "dropped": s["dropped"], "duplicated": s["duplicated"],
                    "partitions": s["partitions"], "acked": s["acked"],
                    "published": s["published"],
                    "deduped": app.metrics.counters.get("deduped_replays")}
        finally:
            await app.stop()

    first = asyncio.run(run())
    second = asyncio.run(run())
    assert first == second
