"""bench.py harness robustness (round-4 verdict ask #2).

Round 2 lost ALL perf evidence to a single transient backend-init failure
(`BENCH_r02.json` rc=1 at `jax.devices()`); the harness must retry bounded
and, on persistent failure, still print ONE parseable JSON line with
``"error": "backend_unavailable"`` and exit 0 so the driver records the
outage instead of a crash.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def test_backend_unavailable_prints_diagnostic_json_line():
    env = dict(os.environ)
    # Force backend init to fail fast and deterministically: an unknown
    # platform makes jax.devices() raise in both the probe subprocess and
    # (hypothetically) in-process. PALLAS_AXON_POOL_IPS must go too —
    # with it set, the machine's sitecustomize dials the TPU relay at
    # INTERPRETER START of every subprocess, which hangs when the shared
    # backend is down (observed this round) and would hang this test.
    env["JAX_PLATFORMS"] = "definitely_not_a_backend"
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--init-retries", "2", "--init-delay", "0"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines!r}"
    payload = json.loads(lines[0])
    assert payload["error"] == "backend_unavailable"
    assert payload["value"] is None
    assert payload["unit"] == "matches/sec"
    # Retry really was bounded: stderr shows the retry log line.
    assert "retry 1/1" in proc.stderr


def test_init_backend_happy_path_unchanged():
    """On a working backend (CPU here), init_backend returns devices on the
    first attempt with no retries."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # see above: no relay dial in tests
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import bench\n"
        "devs = bench.init_backend(attempts=1, delay_s=0)\n"
        "assert devs, devs\n"
        "print('OK', len(devs))\n" % REPO
    )
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=300, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.startswith("OK")
