"""Self-driving service worker: boot the app from env (the same snapshot
plumbing ``service.multiproc`` workers use), offer a Poisson request load to
its own in-process broker, and write one JSON result line to a file.

Why this exists: the environment has no RabbitMQ (SURVEY.md §7 [ENV]), so a
multi-process ingress benchmark cannot drive N workers through a shared
network broker. Each worker instead drives itself — the full ingress path
(broker → decode → middleware → batcher → engine → publish) runs in-process,
which is exactly the per-consumer work the reference fans out across AMQP
consumers. The supervisor-level bench (bench.py --multiproc phase) spawns N
of these via WorkerSupervisor and sums the per-worker throughput.

Overload mode (``--offered-rate``, ISSUE 5): the offered rate may exceed
the service's clearing rate on purpose — the report then accounts for every
response class (matched / queued / shed / timeout / error) instead of only
matches, and stamps per-request deadlines (``--deadline-ms``) so the
deadline-propagation path is exercised. The seeded overload soak
(tests/test_overload.py) and bench.py's multiproc phase both drive this
entry point.

Tiered mode (``--tier-mix``, ISSUE 7): offer a per-class load — e.g.
``0:0.2,1:0.5,2:0.3`` sends 20% tier-0 / 50% tier-1 / 30% tier-2, each
request stamped with its ``x-tier`` header — and account every response
class PER TIER (the loadgen assigned each correlation id its tier, so the
split needs no tier echo from the service). The tier draw is a pure
function of the seed, so a tiered soak replays bit-identically.

Env contract (set by the bench on top of the multiproc worker env; each has
a CLI flag that wins when both are given):
    MM_LOADGEN_RATE         offered req/s (Poisson)      (--offered-rate)
    MM_LOADGEN_SECONDS      measured duration            (--seconds)
    MM_LOADGEN_SEED         arrival/rating RNG seed      (--seed)
    MM_LOADGEN_DEADLINE_MS  per-request deadline, 0=off  (--deadline-ms)
    MM_LOADGEN_TIER_MIX     tier mix, "" = untiered      (--tier-mix)
    MM_LOADGEN_QUALITY      "1" = quality accounting     (--quality)
    MM_LOADGEN_OUT          path for the JSON result     (--out)
"""

from __future__ import annotations

import asyncio
import json
import os
import time

import numpy as np

#: Response classes tallied from reply bodies (cheap substring probes — at
#: overload rates a full json.loads per reply would bill the loadgen, not
#: the service, for the decode).
_STATUS_PROBES = (
    ("matched", b'"status":"matched"'),
    ("queued", b'"status":"queued"'),
    ("shed", b'"status":"shed"'),
    ("timeout", b'"status":"timeout"'),
    ("error", b'"status":"error"'),
)


def parse_tier_mix(spec: str) -> "dict[int, float] | None":
    """``"0:0.2,1:0.5,2:0.3"`` → {0: 0.2, 1: 0.5, 2: 0.3} (weights
    normalized); ""/None → None (untiered)."""
    if not spec:
        return None
    mix: dict[int, float] = {}
    for part in spec.split(","):
        t, _, w = part.partition(":")
        mix[int(t)] = float(w)
    total = sum(mix.values())
    if total <= 0:
        raise ValueError(f"tier mix has no mass: {spec!r}")
    return {t: w / total for t, w in sorted(mix.items())}


async def offered_load(app, queue: str, *, rate: float, duration: float,
                       seed: int, deadline_s: float = 0.0,
                       tier_mix: "dict[int, float] | None" = None,
                       reply_q: str = "loadgen.replies",
                       drain_polls: int = 200,
                       quality_stats: bool = False,
                       rating_sigma: float | None = None) -> dict:
    """Offer a seeded Poisson load to ``app``'s broker and account for
    every response class. Reusable by the CLI below, bench.py's workers,
    and the overload soak (tests/test_overload.py) — one load driver, not
    three drifting copies.

    Consecutive near-equal ratings: arrivals pair off almost immediately,
    keeping the pool small so the measured cost is INGRESS (decode →
    middleware → batcher → publish) — or, when ``rate`` exceeds the
    clearing rate, ADMISSION (the shed path).

    ``tier_mix`` (tier → weight) stamps a seeded ``x-tier`` per arrival
    and splits the accounting per tier (statuses + matched-latency p99) —
    correlation ids carry the assignment, so the per-tier split is exact
    even for response bodies that don't echo the tier.

    ``quality_stats`` (ISSUE 8) parses every MATCHED reply for the match
    ``quality``, the engine-observed ``waited_ms``, and the wire
    ``latency_ms`` — the client-observed/engine-observed wait cross-check:
    ``wait_gap_ms_mean`` = mean(latency − waited), the collect+publish
    queueing the engine did NOT charge the match for. Costs one json.loads
    per matched reply (like tiered runs).
    """
    from matchmaking_tpu.service.broker import Properties
    from matchmaking_tpu.service.overload import stamp_deadline, stamp_tier

    app.broker.declare_queue(reply_q)
    tally = {name: 0 for name, _ in _STATUS_PROBES}
    tally["replies"] = 0
    tier_of_corr: dict[str, int] = {}
    per_tier: dict[int, dict] = {}
    if tier_mix:
        per_tier = {t: {**{name: 0 for name, _ in _STATUS_PROBES},
                        "offered": 0, "latencies_ms": []}
                    for t in tier_mix}

    #: quality_stats rows: (quality, waited_ms, latency_ms) per matched
    #: reply.
    q_rows: list[tuple[float, float, float]] = []

    async def on_reply(delivery) -> None:
        tally["replies"] += 1
        body = bytes(delivery.body)
        status = ""
        for name, probe in _STATUS_PROBES:
            if probe in body:
                tally[name] += 1
                status = name
                break
        if quality_stats and status == "matched":
            try:
                d = json.loads(body)
                q_rows.append((
                    float((d.get("match") or {}).get("quality", 0.0)),
                    float(d.get("waited_ms", 0.0)),
                    float(d.get("latency_ms", 0.0))))
            except (ValueError, TypeError):
                pass
        if not per_tier or not status:
            return
        t = tier_of_corr.get(delivery.properties.correlation_id)
        if t is None:
            return
        row = per_tier[t]
        row[status] += 1
        if status == "matched":
            # Tiered runs pay one json.loads per MATCHED reply for the
            # per-tier latency split; the untiered path keeps the cheap
            # substring probes.
            try:
                row["latencies_ms"].append(
                    float(json.loads(body).get("latency_ms", 0.0)))
            except (ValueError, TypeError):
                pass

    tag = app.broker.basic_consume(reply_q, on_reply, prefetch=1_000_000)

    # Counter BASELINES: shed/expired are app-lifetime monotone counters,
    # and this driver is reused (warmup + measured phases, soak re-runs) —
    # reporting deltas keeps a second call from inheriting the first's.
    counters = app.metrics.counters
    shed0 = counters.get("shed_requests")
    expired0 = counters.get("expired_requests")
    tier_base = {t: (counters.get(f"shed_requests_t{t}"),
                     counters.get(f"expired_requests_t{t}"))
                 for t in (tier_mix or ())}

    rng = np.random.default_rng(seed)
    n_max = int(rate * duration * 2) + 16
    # Default (rating_sigma=None): consecutive near-equal ratings, so the
    # measured cost is ingress/admission (see the docstring). A quality/
    # frontier run wants the OPPOSITE — iid diverse ratings, so the rating
    # threshold actually bites and wait/quality trade off.
    if rating_sigma is None:
        ratings = np.repeat(rng.normal(1500.0, 300.0, size=n_max // 2 + 1), 2)
    else:
        ratings = rng.normal(1500.0, rating_sigma, size=n_max)
    gaps = rng.exponential(1.0 / rate, size=n_max)
    sched = np.cumsum(gaps)
    tiers = None
    if tier_mix:
        # Seeded per-arrival tier draw (pure function of the seed, drawn
        # up front like ratings/gaps — replay-identical by construction).
        tiers = rng.choice(np.fromiter(tier_mix, np.int64, len(tier_mix)),
                           size=n_max,
                           p=np.fromiter(tier_mix.values(), np.float64,
                                         len(tier_mix)))
    t0 = time.perf_counter()
    i = 0
    while i < n_max and sched[i] <= duration:
        now_rel = time.perf_counter() - t0
        while i < n_max and sched[i] <= min(now_rel, duration):
            pid = f"g{seed}_{i}"
            headers: dict = {}
            if deadline_s > 0:
                stamp_deadline(headers, time.time(), deadline_s)
            if tiers is not None:
                t = int(tiers[i])
                stamp_tier(headers, t)
                tier_of_corr[pid] = t
                per_tier[t]["offered"] += 1
            app.broker.publish(
                queue,
                f'{{"id":"{pid}","rating":{ratings[i]:.2f}}}'.encode(),
                Properties(reply_to=reply_q, correlation_id=pid,
                           headers=headers))
            i += 1
        if i < n_max and sched[i] > now_rel:
            await asyncio.sleep(min(sched[i] - now_rel, 0.005))
    span = time.perf_counter() - t0
    for _ in range(drain_polls):
        await asyncio.sleep(0.025)
        if (app.broker.queue_depth(queue) == 0
                and app.broker.handlers_idle()):
            break
    app.broker.basic_cancel(tag)
    result = {
        "queue": queue,
        "offered_req_s": rate,
        "sent": i,
        "sent_req_s": round(i / span, 1),
        "players_matched": tally["matched"],
        "matched_per_s": round(tally["matched"] / span, 1),
        "replies": tally["replies"],
        "queued_acks": tally["queued"],
        "shed": tally["shed"],
        "timeout": tally["timeout"],
        "error": tally["error"],
        "shed_requests": int(counters.get("shed_requests") - shed0),
        "expired_requests": int(counters.get("expired_requests") - expired0),
    }
    if quality_stats:
        if q_rows:
            # np.array, not asarray: the blocking-call rule flags asarray
            # in async bodies (device-sync hazard); this is host data.
            arr = np.array(q_rows, np.float64)
            qual, waited, lat = arr[:, 0], arr[:, 1], arr[:, 2]
            gap = lat - waited
            result["quality"] = {
                "matched": len(q_rows),
                "quality_mean": round(float(qual.mean()), 6),
                "quality_p10": round(float(np.percentile(qual, 10)), 6),
                "quality_p50": round(float(np.percentile(qual, 50)), 6),
                "waited_ms_p50": round(float(np.percentile(waited, 50)), 3),
                "waited_ms_p99": round(float(np.percentile(waited, 99)), 3),
                "latency_ms_p99": round(float(np.percentile(lat, 99)), 3),
                # Client-observed minus engine-observed wait: the
                # collect/publish queueing the engine did not charge the
                # match for — cross-checkable against attribution's
                # publish_lag/readback categories.
                "wait_gap_ms_mean": round(float(gap.mean()), 3),
            }
        else:
            result["quality"] = {"matched": 0}
    if per_tier:
        result["tiers"] = {
            str(t): {
                "offered": row["offered"],
                "matched": row["matched"],
                "queued_acks": row["queued"],
                "shed": row["shed"],
                "timeout": row["timeout"],
                "error": row["error"],
                "p99_ms": (round(float(np.percentile(
                    row["latencies_ms"], 99)), 3)
                    if row["latencies_ms"] else None),
                "shed_requests": int(counters.get(f"shed_requests_t{t}")
                                     - tier_base[t][0]),
                "expired_requests": int(
                    counters.get(f"expired_requests_t{t}")
                    - tier_base[t][1]),
            }
            for t, row in sorted(per_tier.items())
        }
    return result


async def _run(args) -> dict:
    from matchmaking_tpu.config import Config
    from matchmaking_tpu.service.app import MatchmakingApp

    cfg = Config.from_env()
    app = MatchmakingApp(cfg)
    await app.start()
    result = await offered_load(
        app, cfg.queues[0].name,
        rate=args.offered_rate, duration=args.seconds, seed=args.seed,
        deadline_s=args.deadline_ms / 1e3 if args.deadline_ms > 0 else 0.0,
        tier_mix=parse_tier_mix(args.tier_mix),
        quality_stats=bool(args.quality))
    result["pid"] = os.getpid()
    await app.stop()
    return result


def _parse_args(argv=None):
    import argparse

    env = os.environ
    p = argparse.ArgumentParser(
        description="self-driving offered-load worker (overload mode: set "
                    "--offered-rate above the clearing rate and read the "
                    "shed/timeout accounting)")
    p.add_argument("--offered-rate", type=float,
                   default=float(env.get("MM_LOADGEN_RATE", "10000")),
                   help="offered req/s (Poisson)")
    p.add_argument("--seconds", type=float,
                   default=float(env.get("MM_LOADGEN_SECONDS", "4")),
                   help="measured duration")
    p.add_argument("--seed", type=int,
                   default=int(env.get("MM_LOADGEN_SEED", str(os.getpid()))),
                   help="arrival/rating RNG seed (defaults to the pid so "
                        "multiproc workers don't correlate)")
    p.add_argument("--deadline-ms", type=float,
                   default=float(env.get("MM_LOADGEN_DEADLINE_MS", "0")),
                   help="stamp x-deadline on every request (0 = off)")
    p.add_argument("--tier-mix",
                   default=env.get("MM_LOADGEN_TIER_MIX", ""),
                   help="per-class offered load, e.g. '0:0.2,1:0.5,2:0.3' "
                        "— stamps a seeded x-tier per arrival and splits "
                        "the response accounting per tier ('' = untiered)")
    p.add_argument("--quality", action="store_true",
                   default=env.get("MM_LOADGEN_QUALITY", "") == "1",
                   help="parse matched replies for match quality + the "
                        "engine-observed waited_ms and report the "
                        "client/engine wait cross-check (ISSUE 8)")
    p.add_argument("--out", default=env.get("MM_LOADGEN_OUT", ""),
                   help="path for the one-line JSON result")
    return p.parse_args(argv)


def main(argv=None) -> None:
    args = _parse_args(argv)
    result = asyncio.run(_run(args))
    line = json.dumps(result, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line, flush=True)


if __name__ == "__main__":
    main()
