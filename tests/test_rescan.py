"""Periodic rescan: threshold widening must resolve BETWEEN waiting pool
members (matching is otherwise arrival-triggered — reference semantics)."""

import asyncio

import numpy as np
import pytest

from matchmaking_tpu.config import (
    BatcherConfig,
    Config,
    EngineConfig,
    QueueConfig,
)
from matchmaking_tpu.engine.cpu import CpuEngine
from matchmaking_tpu.engine.interface import make_engine
from matchmaking_tpu.service.contract import SearchRequest


def _q(**kw):
    return QueueConfig(rating_threshold=10.0, widen_per_sec=10.0,
                       max_threshold=200.0, **kw)


def _cfg(q):
    return Config(queues=(q,), engine=EngineConfig(
        backend="tpu", pool_capacity=64, pool_block=64, batch_buckets=(16,)))


def _req(i, rating, t=0.0):
    return SearchRequest(id=f"p{i}", rating=float(rating), enqueued_at=t,
                         reply_to=f"rq.p{i}")


class TestEngineRescan:
    def test_widening_resolves_between_pool_members(self):
        q = _q()
        eng = make_engine(_cfg(q), q)
        # Distance 40; both thresholds widen 10/s from base 10.
        eng.restore([_req(0, 1500.0, 0.0), _req(1, 1540.0, 0.0)], 0.0)

        tok = eng.rescan_async(16, now=1.0)   # eff thr 20 < 40: no match
        assert tok is not None
        outs = eng.flush()
        assert sum(o.n_matches for _, o in outs) == 0
        assert eng.pool_size() == 2

        eng.rescan_async(16, now=4.0)         # eff thr 50 ≥ 40: match
        outs = eng.flush()
        assert sum(o.n_matches for _, o in outs) == 1
        out = outs[0][1]
        assert {out.m_id_a[0], out.m_id_b[0]} == {"p0", "p1"}
        # Quality from both sides' widened thresholds: 1 - 40/min(50,50).
        assert out.m_quality[0] == pytest.approx(1.0 - 40.0 / 50.0, abs=1e-5)
        assert eng.pool_size() == 0

    def test_empty_pool_returns_none(self):
        q = _q()
        eng = make_engine(_cfg(q), q)
        assert eng.rescan_async(16, now=1.0) is None

    def test_rescan_overlaps_in_flight_window_without_double_match(self):
        """The no-admission rescan step (kernels._rescan_step) makes the
        round-4 hazard structurally impossible: a rescan dispatched while a
        window is IN FLIGHT builds its lanes from the stale mirror — which
        still lists players the in-flight window is matching — but the
        device-side active gate turns those lanes into no-ops instead of
        resurrecting them."""
        q = _q()
        eng = make_engine(_cfg(q), q)
        # A and C wait (restore never matches); B arrives and will take A
        # (d=2 beats d=3) in a window we deliberately do NOT collect.
        eng.restore([_req(0, 1500.0, 0.0), _req(1, 1505.0, 0.0)], 0.0)
        eng.search_async([_req(2, 1502.0, 0.0)], 0.5)          # in flight
        tok = eng.rescan_async(16, now=1.0)                    # overlapped
        assert tok is not None and tok in eng.rescan_tokens
        outs = dict(eng.flush())
        matched = []
        for out in outs.values():
            if hasattr(out, "m_id_a"):           # columnar (the rescan)
                matched += list(out.m_id_a) + list(out.m_id_b)
            else:                                # object (the search window)
                matched += [r.id for m in out.matches for t in m.teams
                            for r in t]
        assert sorted(matched) == ["p0", "p2"]   # A+B once; C untouched
        assert eng.pool_size() == 1              # C still waits
        # The token stays routable until a collector consumes it (the
        # service's _finish_token discards it when publishing).
        assert tok in eng.rescan_tokens
        eng.rescan_tokens.discard(tok)

    def test_multi_chunk_rescan_resolves_whole_pool_in_one_tick(self):
        """rescan_window > one bucket: the tick spans multiple no-admission
        chunks, so pool-wide widening resolution no longer takes one bucket
        per tick — and chunks cannot double-match across each other (later
        chunks see earlier chunks' retirements via the device pool).
        pipeline_depth=3 budgets the tick at 3 chunks × 16 lanes ≥ the
        40-player pool (the per-tick chunk cap is tested below)."""
        q = _q()
        cfg = Config(queues=(q,), engine=EngineConfig(
            backend="tpu", pool_capacity=64, pool_block=64,
            batch_buckets=(16,), pipeline_depth=3))
        eng = make_engine(cfg, q)       # buckets (16,); threshold 80
        # 20 latent pairs, pair i at rating 5000*i (+0/+5): partners match
        # (d=5), nothing else comes close. 40 players = 3 chunks of 16.
        reqs = []
        for i in range(20):
            reqs.append(_req(2 * i, 5000.0 * i, 0.0))
            reqs.append(_req(2 * i + 1, 5000.0 * i + 5.0, 0.0))
        eng.restore(reqs, 0.0)
        assert eng.rescan_async(64, now=1.0) is not None
        outs = dict(eng.flush())
        pairs = set()
        for out in outs.values():
            for a, b in zip(out.m_id_a, out.m_id_b):
                pairs.add(tuple(sorted((a, b))))
        assert len(pairs) == 20
        assert all(int(a[1:]) // 2 == int(b[1:]) // 2 for a, b in pairs)
        assert eng.pool_size() == 0

    def test_rescan_tick_chunk_budget_caps_device_steps(self):
        """A pool-sized rescan window must not queue unbounded device steps
        ahead of traffic: one tick dispatches at most pipeline_depth chunks
        (ADVICE round-5 #1), and oldest-first selection rolls the remainder
        into the next tick."""
        q = _q()
        eng = make_engine(_cfg(q), q)   # buckets (16,); pipeline_depth 2
        # 24 latent pairs far apart: partners match (d=5) once widened.
        reqs = []
        for i in range(24):
            reqs.append(_req(2 * i, 5000.0 * i, 0.0))
            reqs.append(_req(2 * i + 1, 5000.0 * i + 5.0, 0.0))
        eng.restore(reqs, 0.0)
        tok = eng.rescan_async(64, now=1.0)  # asks for 64 > 2 × 16 budget
        assert tok is not None
        assert len(eng._pending[-1].chunks) == 2   # capped, not 4
        outs = dict(eng.flush())
        pairs = {tuple(sorted((a, b)))
                 for out in outs.values()
                 for a, b in zip(out.m_id_a, out.m_id_b)}
        assert len(pairs) == 16                    # 32 oldest players
        # Next tick covers the rolled-over remainder.
        eng.rescan_async(64, now=2.0)
        outs = dict(eng.flush())
        pairs |= {tuple(sorted((a, b)))
                  for out in outs.values()
                  for a, b in zip(out.m_id_a, out.m_id_b)}
        assert len(pairs) == 24
        assert eng.pool_size() == 0

    def test_oldest_players_prioritized(self):
        q = _q()
        cfg = Config(queues=(q,), engine=EngineConfig(
            backend="tpu", pool_capacity=64, pool_block=64,
            batch_buckets=(4,)))
        eng = make_engine(cfg, q)
        # 6 players, only a 4-lane rescan bucket: the 4 OLDEST re-submit.
        # Old pair (enqueued t=0) distance 60; young pair (t=9) distance 60.
        eng.restore([_req(0, 1000.0, 0.0), _req(1, 1060.0, 0.0)], 0.0)
        eng.restore([_req(2, 3000.0, 9.0), _req(3, 3060.0, 9.0),
                     _req(4, 5000.0, 9.0), _req(5, 7000.0, 9.0)], 9.0)
        # At t=10: old pair eff thr 110 ≥ 60 (can match); young pair eff
        # thr 20 < 60 (cannot). Only the old pair may match regardless of
        # which 4 got rescanned — but the oldest-first pick must INCLUDE
        # the old pair.
        eng.rescan_async(4, now=10.0)
        outs = eng.flush()
        assert sum(o.n_matches for _, o in outs) == 1
        out = outs[0][1]
        assert {out.m_id_a[0], out.m_id_b[0]} == {"p0", "p1"}

    def test_cpu_oracle_rescan_equivalent(self):
        q = _q()
        tpu = make_engine(_cfg(q), q)
        cpu = CpuEngine(_cfg(q), q)
        reqs = [_req(0, 1500.0), _req(1, 1540.0), _req(2, 1800.0)]
        tpu.restore(reqs, 0.0)
        cpu.restore(reqs, 0.0)

        out_c = cpu.rescan(16, now=4.0)
        tpu.rescan_async(16, now=4.0)
        outs_t = tpu.flush()
        t_pairs = {frozenset((o.m_id_a[j], o.m_id_b[j]))
                   for _, o in outs_t for j in range(o.n_matches)}
        c_pairs = {frozenset(r.id for team in m.teams for r in team)
                   for m in out_c.matches}
        assert t_pairs == c_pairs == {frozenset(("p0", "p1"))}
        assert tpu.pool_size() == cpu.pool_size() == 1


class TestServiceRescan:
    def test_service_rescan_publishes_matches(self):
        from matchmaking_tpu.service.app import MatchmakingApp
        from matchmaking_tpu.service.client import MatchmakingClient

        async def run():
            q = QueueConfig(rating_threshold=1.0, widen_per_sec=50.0,
                            max_threshold=500.0, rescan_interval_s=0.1)
            cfg = Config(
                queues=(q,),
                engine=EngineConfig(backend="tpu", pool_capacity=64,
                                    pool_block=64, batch_buckets=(16,)),
                batcher=BatcherConfig(max_batch=16, max_wait_ms=1.0),
            )
            app = MatchmakingApp(cfg)
            await app.start()
            try:
                client = MatchmakingClient(app.broker, q.name)
                # Distance 30 ≫ base threshold 1; widens past 30 in <1 s.
                ra = client.submit({"id": "a", "rating": 1500})
                rb = client.submit({"id": "b", "rating": 1530})
                qa = await client.next_response(ra, timeout=15.0)
                qb = await client.next_response(rb, timeout=15.0)
                assert qa.status == "queued" and qb.status == "queued"
                ma = await client.next_response(ra, timeout=15.0)
                mb = await client.next_response(rb, timeout=15.0)
                assert ma is not None and ma.status == "matched"
                assert mb is not None and mb.status == "matched"
                assert set(ma.match.players) == {"a", "b"}
                assert app.metrics.counters.get("rescan_matches") >= 1
            finally:
                await app.stop()

        asyncio.run(run())


def test_device_team_rescan_resolves_widening():
    """Two 2v2 groups too far apart at enqueue time: with widening, a
    rescan tick must form the match under ZERO traffic (round-4: the team
    step's window formation is pool-wide, so an all-invalid batch re-runs
    it with current effective thresholds)."""
    from matchmaking_tpu.service.contract import SearchRequest

    cfg = Config(
        queues=(QueueConfig(team_size=2, rating_threshold=10.0,
                            widen_per_sec=10.0, max_threshold=400.0),),
        engine=EngineConfig(backend="tpu", pool_capacity=64, pool_block=64,
                            batch_buckets=(16,)),
    )
    engine = make_engine(cfg, cfg.queues[0])
    reqs = [SearchRequest(id=f"p{i}", rating=1500.0 + 30.0 * i,
                          region="eu", game_mode="std", enqueued_at=1.0)
            for i in range(4)]  # spread 90 > threshold 10 at t=1
    out = engine.search(reqs, now=1.0)
    assert not out.matches and engine.pool_size() == 4
    # t=1: no match. t=31: widened by 300 -> spread 90 fits.
    tok = engine.rescan_async(16, 31.0)
    assert tok is not None
    outs = dict(engine.flush())
    assert engine.device_error is None
    matches = outs[tok].matches
    assert len(matches) == 1
    ids = {r.id for t in matches[0].teams for r in t}
    assert ids == {"p0", "p1", "p2", "p3"}
    assert engine.pool_size() == 0


def test_service_team_rescan_end_to_end():
    """Service-level: a device team queue with widening + rescan ticks
    matches waiting groups under ZERO follow-up traffic (rescan outcomes
    flow through _rescan_loop's object-outcome branch with the pipelined
    drain)."""
    import asyncio

    from matchmaking_tpu.config import BatcherConfig
    from matchmaking_tpu.service.app import MatchmakingApp
    from matchmaking_tpu.service.client import MatchmakingClient

    async def run():
        cfg = Config(
            queues=(QueueConfig(team_size=2, rating_threshold=10.0,
                                widen_per_sec=50.0, max_threshold=400.0,
                                rescan_interval_s=0.2),),
            engine=EngineConfig(backend="tpu", pool_capacity=64,
                                pool_block=64, batch_buckets=(8,)),
            batcher=BatcherConfig(max_batch=4, max_wait_ms=5.0),
        )
        app = MatchmakingApp(cfg)
        await app.start()
        c = MatchmakingClient(app.broker, "matchmaking.search")
        # Spread 90 > threshold 10 at enqueue; widening (50/s) makes the
        # 4-player window valid within ~2 s — only rescan ticks can see it.
        handles = {f"p{i}": c.submit({"id": f"p{i}", "rating": 1500 + 30 * i,
                                      "region": "eu", "game_mode": "std"})
                   for i in range(4)}
        matched = set()
        for pid, h in handles.items():
            r = await c.next_response(h, timeout=30.0)
            while r.status == "queued":
                r = await c.next_response(h, timeout=30.0)
            assert r.status == "matched", (pid, r)
            matched.add(pid)
        assert matched == set(handles)
        # one 2v2 match, formed by a rescan tick (counter counts MATCHES)
        assert app.metrics.counters.get("rescan_matches") >= 1
        await app.stop()

    asyncio.run(run())
