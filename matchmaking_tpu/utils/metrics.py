"""Metrics/observability: counters, latency percentiles, stage spans.

The reference leans on Elixir ``Logger`` and BEAM introspection; the rebuild
makes the BASELINE headline numbers (matches/sec, p50/p99 end-to-end latency,
pool occupancy, batch fill, recompile count) first-class (SURVEY.md §5
"Metrics/logging/observability"). Pure stdlib, no deps.
"""

from __future__ import annotations

import json
import math
import time
from collections import defaultdict
from dataclasses import dataclass, field


class Counter:
    def __init__(self) -> None:
        self._values: dict[str, float] = defaultdict(float)

    def inc(self, name: str, value: float = 1.0) -> None:
        self._values[name] += value

    def get(self, name: str) -> float:
        return self._values[name]

    def snapshot(self) -> dict[str, float]:
        return dict(self._values)


class LatencyRecorder:
    """Reservoir-less latency recorder: keeps every sample (bench windows are
    bounded); exposes percentiles the BASELINE metric asks for."""

    def __init__(self) -> None:
        self._samples: list[float] = []

    def record(self, seconds: float) -> None:
        self._samples.append(seconds)

    def __len__(self) -> int:
        return len(self._samples)

    def percentile(self, p: float) -> float:
        if not self._samples:
            return math.nan
        s = sorted(self._samples)
        k = min(len(s) - 1, max(0, math.ceil(p / 100.0 * len(s)) - 1))
        return s[k]

    def summary_ms(self) -> dict[str, float]:
        if not self._samples:
            return {"count": 0}
        return {
            "count": len(self._samples),
            "p50_ms": round(self.percentile(50) * 1e3, 3),
            "p90_ms": round(self.percentile(90) * 1e3, 3),
            "p99_ms": round(self.percentile(99) * 1e3, 3),
            "max_ms": round(max(self._samples) * 1e3, 3),
            "mean_ms": round(sum(self._samples) / len(self._samples) * 1e3, 3),
        }


@dataclass
class Span:
    """Wall-clock span for per-stage latency accounting (batcher wait, H2D,
    kernel, D2H, publish — SURVEY.md §5 tracing plan)."""

    name: str
    start: float = field(default_factory=time.perf_counter)
    elapsed: float = 0.0

    def stop(self) -> float:
        self.elapsed = time.perf_counter() - self.start
        return self.elapsed


class Metrics:
    def __init__(self) -> None:
        self.counters = Counter()
        self.latency: dict[str, LatencyRecorder] = defaultdict(LatencyRecorder)

    def record_latency(self, name: str, seconds: float) -> None:
        self.latency[name].record(seconds)

    def report(self) -> dict:
        return {
            "counters": self.counters.snapshot(),
            "latency": {k: v.summary_ms() for k, v in self.latency.items()},
        }

    def report_json(self) -> str:
        return json.dumps(self.report(), sort_keys=True)
