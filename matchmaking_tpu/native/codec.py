"""ctypes binding for the native batch wire decoder (native/codec.cc).

One C call decodes a window of raw AMQP JSON bodies into RequestColumns
arrays (the engine's columnar fast path); rows flagged NEEDS_PYTHON (parties,
roles, string escapes) or invalid fall back to ``contract.decode_request`` —
the semantic source of truth whose validation the C++ mirrors (equivalence
pinned by tests/test_native_codec.py).

The library builds lazily with g++ (no deps; ~1 s once, cached next to the
source). Everything degrades to pure Python when g++ or the build is
unavailable — the native layer is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native", "codec.cc")
_LIB = os.path.join(os.path.dirname(_SRC), "libmmcodec.so")

# Status codes (keep in sync with codec.cc).
OK = 0
NEEDS_PYTHON = 1
_ERROR_CODES = {
    2: "bad_json",
    3: "missing_field",
    4: "bad_type",
    5: "bad_rating",
    6: "bad_threshold",
}

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_failed = False


def _load() -> ctypes.CDLL | None:
    """Build (once) and load the shared library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB, _SRC],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_LIB)
            lib.mm_decode_requests.restype = ctypes.c_int64
            lib.mm_decode_requests.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),          # bufs
                np.ctypeslib.ndpointer(np.int32),         # lens
                ctypes.c_int32,                           # n
                np.ctypeslib.ndpointer(np.float32),       # rating
                np.ctypeslib.ndpointer(np.float32),       # rd
                np.ctypeslib.ndpointer(np.float32),       # threshold
                np.ctypeslib.ndpointer(np.int32),         # status
                ctypes.c_char_p,                          # arena
                ctypes.c_int64,                           # cap
                np.ctypeslib.ndpointer(np.int64),         # id_off
                np.ctypeslib.ndpointer(np.int64),         # region_off
                np.ctypeslib.ndpointer(np.int64),         # mode_off
            ]
            lib.mm_encode_matched.restype = ctypes.c_int64
            lib.mm_encode_matched.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),          # id_a
                ctypes.POINTER(ctypes.c_char_p),          # id_b
                ctypes.POINTER(ctypes.c_char_p),          # match_id
                ctypes.c_int32,                           # n
                np.ctypeslib.ndpointer(np.float64),       # lat_a
                np.ctypeslib.ndpointer(np.float64),       # lat_b
                np.ctypeslib.ndpointer(np.float64),       # quality
                ctypes.c_char_p,                          # arena
                ctypes.c_int64,                           # cap
                np.ctypeslib.ndpointer(np.int64),         # off
            ]
            _lib = lib
        except Exception:
            log.exception("native codec unavailable; using pure-Python decode")
            _build_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def decode_batch(bodies: list[bytes]):
    """Decode a window of JSON bodies natively.

    Returns (ids, rating, rd, threshold, region_names, mode_names, status)
    where string columns are object arrays ("" region/mode = wildcard) and
    ``status`` is int32 per row (OK / NEEDS_PYTHON / error codes — map via
    ``error_code``). Returns None when the native library is unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    n = len(bodies)
    lens = np.fromiter((len(b) for b in bodies), np.int32, n)
    bufs = (ctypes.c_char_p * n)(*bodies)
    rating = np.empty(n, np.float32)
    rd = np.empty(n, np.float32)
    threshold = np.empty(n, np.float32)
    status = np.empty(n, np.int32)
    id_off = np.empty(n + 1, np.int64)
    region_off = np.empty(n + 1, np.int64)
    mode_off = np.empty(n + 1, np.int64)
    cap = int(lens.sum()) + 16
    arena = ctypes.create_string_buffer(cap)
    used = lib.mm_decode_requests(
        bufs, lens, n, rating, rd, threshold, status, arena, cap,
        id_off, region_off, mode_off)
    if used < 0:  # arena overflow cannot happen (strings ⊆ input), but guard
        return None
    raw = arena.raw
    ids = np.empty(n, object)
    regions = np.empty(n, object)
    modes = np.empty(n, object)
    for i in range(n):
        if status[i] == OK:
            ids[i] = raw[id_off[i]:region_off[i]].decode()
            regions[i] = raw[region_off[i]:mode_off[i]].decode()
            modes[i] = raw[mode_off[i]:id_off[i + 1]].decode()
        else:
            ids[i] = regions[i] = modes[i] = ""
    return ids, rating, rd, threshold, regions, modes, status


def error_code(status: int) -> str:
    return _ERROR_CODES.get(int(status), "bad_json")


def encode_matched_batch(ids_a, ids_b, match_ids, lat_a_ms, lat_b_ms,
                         quality):
    """Encode 2n matched-response bodies natively (a0, b0, a1, b1, ...).

    Inputs are sequences of str (ids) and float64 arrays (latencies in ms,
    match quality). Returns a list of 2n ``bytes`` bodies matching
    ``contract.encode_response``'s schema (parsed-value equivalence pinned
    by tests/test_native_codec.py), or None when the native library is
    unavailable — callers fall back to the Python encoder.
    """
    lib = _load()
    if lib is None:
        return None
    n = len(match_ids)
    if n == 0:
        return []
    lat_a_ms = np.ascontiguousarray(lat_a_ms, np.float64)
    lat_b_ms = np.ascontiguousarray(lat_b_ms, np.float64)
    quality = np.ascontiguousarray(quality, np.float64)
    if not (np.isfinite(lat_a_ms).all() and np.isfinite(lat_b_ms).all()
            and np.isfinite(quality).all()):
        return None  # NaN/inf are not strict JSON; Python encoder handles
    a_bytes = [s.encode() for s in ids_a]
    b_bytes = [s.encode() for s in ids_b]
    m_bytes = [s.encode() for s in match_ids]
    if any(b"\x00" in s for s in a_bytes) or any(b"\x00" in s for s in b_bytes):
        # c_char_p is NUL-terminated: an embedded NUL in an id would be
        # silently truncated, corrupting the body AND its dedup-replay
        # copy. Pathological ids take the Python encoder.
        return None
    a_ptrs = (ctypes.c_char_p * n)(*a_bytes)
    b_ptrs = (ctypes.c_char_p * n)(*b_bytes)
    m_ptrs = (ctypes.c_char_p * n)(*m_bytes)
    lat_a, lat_b, qual = lat_a_ms, lat_b_ms, quality
    off = np.empty(2 * n + 1, np.int64)
    # Fixed part ≈ 120 B/response + 4 id copies + match id; escapes can at
    # worst 6x a string, hence the generous per-row bound with retry.
    cap = 256 * 2 * n + 8 * sum(len(s) for s in a_bytes + b_bytes + m_bytes)
    for _ in range(2):
        arena = ctypes.create_string_buffer(cap)
        used = lib.mm_encode_matched(a_ptrs, b_ptrs, m_ptrs, n, lat_a, lat_b,
                                     qual, arena, cap, off)
        if used >= 0:
            raw = arena.raw
            return [raw[off[j]:off[j + 1]] for j in range(2 * n)]
        cap *= 4
    return None  # pragma: no cover - bound above cannot be exceeded twice
