"""AmqpBroker (the RabbitMQ deployment seam) against the in-memory pika
fake — publish/consume/ack, rpc, and the reference's recovery semantics:
connection death → backoff reconnect → redeclare → resubscribe, unacked
deliveries requeued, stale-generation acks dropped (SURVEY.md §3 Entry 4)."""

import asyncio
import uuid

import pytest

from matchmaking_tpu.service.amqp_transport import AmqpBroker
from matchmaking_tpu.service.broker import Properties
from matchmaking_tpu.testing import fake_pika
from matchmaking_tpu.testing.fake_pika import FakeServer, wait_until


def make_broker(url=None, **kw):
    url = url or f"amqp://fake-{uuid.uuid4().hex[:8]}"
    kw.setdefault("reconnect_base_s", 0.01)
    kw.setdefault("reconnect_max_s", 0.05)
    broker = AmqpBroker(url, pika_module=fake_pika, **kw)
    return broker, FakeServer.for_url(url)


async def drain(seconds=0.0):
    await asyncio.sleep(seconds)


@pytest.mark.asyncio
async def test_publish_consume_ack_roundtrip():
    broker, server = make_broker()
    got = []

    async def on_delivery(d):
        got.append(d)
        broker.ack(tag, d.delivery_tag)

    broker.declare_queue("q1")
    tag = broker.basic_consume("q1", on_delivery)
    broker.publish("q1", b"hello", Properties(reply_to="rq", correlation_id="c1"))
    for _ in range(200):
        if got:
            break
        await drain(0.01)
    assert got and got[0].body == b"hello"
    assert got[0].properties.reply_to == "rq"
    assert got[0].properties.correlation_id == "c1"
    # Ack is dispatched via add_callback_threadsafe; wait until applied.
    consumer = broker._consumers[tag]
    assert wait_until(lambda: not consumer.channel._unacked)
    assert broker.stats["acked"] == 1
    assert broker.queue_depth("q1") == 0
    broker.close()


@pytest.mark.asyncio
async def test_rpc_roundtrip():
    broker, server = make_broker()

    async def on_request(d):
        broker.publish(d.properties.reply_to, b"pong:" + d.body,
                       Properties(correlation_id=d.properties.correlation_id))
        broker.ack(tag, d.delivery_tag)

    broker.declare_queue("rpc.q")
    tag = broker.basic_consume("rpc.q", on_request)
    reply = await broker.rpc("rpc.q", b"ping", timeout=5.0)
    assert reply == b"pong:ping"
    broker.close()


@pytest.mark.asyncio
async def test_connection_kill_reconnects_and_requeues():
    """Kill every connection mid-stream: the consumer must reconnect,
    resubscribe, and see the unacked delivery again (redelivered), plus
    messages published after the outage."""
    broker, server = make_broker()
    got = []
    hold_acks = True

    async def on_delivery(d):
        got.append(d)
        if not hold_acks:
            broker.ack(tag, d.delivery_tag)

    broker.declare_queue("q2")
    tag = broker.basic_consume("q2", on_delivery)
    consumer = broker._consumers[tag]
    assert consumer.connected.wait(2.0)

    broker.publish("q2", b"m1")
    for _ in range(200):
        if got:
            break
        await drain(0.01)
    assert [d.body for d in got] == [b"m1"]
    first_tag = got[0].delivery_tag

    # Sever everything while m1 is still unacked.
    consumer.connected.clear()
    server.kill_connections()
    assert wait_until(lambda: consumer.connected.is_set(), timeout=5.0)
    assert broker.stats["consumer_reconnects"] >= 1

    hold_acks = False
    broker.publish("q2", b"m2")     # main connection also reconnects
    for _ in range(400):
        if len(got) >= 3:
            break
        await drain(0.01)
    bodies = [d.body for d in got]
    assert bodies.count(b"m1") == 2, bodies    # requeued redelivery
    assert b"m2" in bodies
    redelivs = [d for d in got[1:] if d.body == b"m1"]
    assert redelivs[0].redelivered
    assert broker.stats["reconnects"] >= 1

    # Acking the PRE-KILL delivery tag must be dropped as stale, not
    # poison the new channel.
    stale_before = broker.stats["stale_acks"]
    broker.ack(tag, first_tag)
    assert broker.stats["stale_acks"] == stale_before + 1
    assert consumer.connected.is_set()

    # The redelivered copies were acked on the new generation: queue drains.
    assert wait_until(lambda: broker.queue_depth("q2") == 0)
    broker.close()


@pytest.mark.asyncio
async def test_reconnect_waits_out_server_downtime():
    """While the server is down even new dials fail; ops retry with
    backoff until it returns (supervisor-restart semantics)."""
    broker, server = make_broker()
    broker.declare_queue("q3")
    server.set_down(True)

    async def bring_back():
        await drain(0.05)
        server.set_down(False)

    task = asyncio.create_task(bring_back())
    # publish() blocks through the outage and succeeds after recovery.
    await asyncio.get_event_loop().run_in_executor(
        None, lambda: broker.publish("q3", b"late"))
    await task
    assert broker.queue_depth("q3") == 1
    assert broker.stats["reconnects"] >= 1
    broker.close()


@pytest.mark.asyncio
async def test_queue_redeclared_after_reconnect():
    """Queues this adapter declared exist again after the connection is
    re-dialed (redeclare-on-restart), even if the fake lost them."""
    broker, server = make_broker()
    broker.declare_queue("q4")
    server.kill_connections()
    with server.lock:
        server.queues.pop("q4", None)   # simulate a non-durable wipe
    assert broker.queue_depth("q4") == 0   # reconnect + redeclare, no raise
    broker.close()


@pytest.mark.asyncio
async def test_serve_entrypoint_end_to_end(monkeypatch):
    """The Docker CMD path: MM_* env → Config.from_env → AmqpBroker dialing
    MM_BROKER_URL → full service → two players matched over the 'real'
    (fake-pika) AMQP transport from a separate client connection."""
    from matchmaking_tpu.service.app import serve
    from matchmaking_tpu.service.client import MatchmakingClient

    url = f"amqp://serve-{uuid.uuid4().hex[:8]}"
    monkeypatch.setenv("MM_BROKER_URL", url)
    monkeypatch.setenv("MM_ENGINE_BACKEND", "cpu")
    monkeypatch.setenv("MM_BATCHER_MAX_WAIT_MS", "1")
    stop = asyncio.Event()
    task = asyncio.create_task(serve(stop, pika_module=fake_pika))
    try:
        server = FakeServer.for_url(url)
        # async-poll (wait_until would block the loop serve() runs on)
        for _ in range(500):
            if "matchmaking.search" in server.queues:
                break
            await drain(0.01)
        assert "matchmaking.search" in server.queues
        client = AmqpBroker(url, pika_module=fake_pika,
                            reconnect_base_s=0.01)
        mm = MatchmakingClient(client, "matchmaking.search")
        r1, r2 = await asyncio.gather(
            mm.search_until_matched({"id": "alice", "rating": 1500},
                                    timeout=10.0),
            mm.search_until_matched({"id": "bob", "rating": 1503},
                                    timeout=10.0),
        )
        assert r1.status == "matched" and r2.status == "matched"
        assert r1.match.match_id == r2.match.match_id
        client.close()
    finally:
        stop.set()
        await task


@pytest.mark.asyncio
async def test_publish_stamps_trace_header_and_consumer_rebuilds_context():
    """ROADMAP PR 3 follow-up: AMQP traces are stamped VIA MESSAGE HEADERS
    at publish, so the consumer-side context starts at true enqueue time
    and the enqueue stage stops reading 0."""
    import time

    from matchmaking_tpu.service.amqp_transport import TRACE_HEADER

    broker, server = make_broker()
    got = []

    async def on_delivery(d):
        got.append(d)
        broker.ack(tag, d.delivery_tag)

    broker.declare_queue("tq")
    tag = broker.basic_consume("tq", on_delivery)
    t0 = time.time()
    broker.publish("tq", b"x", Properties(reply_to="rq",
                                          correlation_id="c9"))
    for _ in range(200):
        if got:
            break
        await drain(0.01)
    d = got[0]
    assert TRACE_HEADER in d.properties.headers
    assert d.trace is not None
    stage, t_enq = d.trace.marks[0]
    assert stage == "enqueue"
    # The mark is the PUBLISH wall clock (from the header), not consume.
    assert t0 <= t_enq <= time.time()
    assert float(d.properties.headers[TRACE_HEADER]) == t_enq
    # Responses (no reply_to) are never stamped.
    broker.publish("tq", b"resp", Properties(correlation_id="c9"))
    for _ in range(200):
        if len(got) == 2:
            break
        await drain(0.01)
    assert got[1].trace is None
    assert TRACE_HEADER not in got[1].properties.headers
    broker.close()


@pytest.mark.asyncio
async def test_trace_sample_n_stamps_every_nth_amqp_publish():
    from matchmaking_tpu.service.amqp_transport import TRACE_HEADER

    broker, server = make_broker()
    got = []

    async def on_delivery(d):
        got.append(d)
        broker.ack(tag, d.delivery_tag)

    broker.trace_sample_n = 3
    broker.declare_queue("sq")
    tag = broker.basic_consume("sq", on_delivery)
    for i in range(9):
        broker.publish("sq", b"x", Properties(reply_to="rq",
                                              correlation_id=f"c{i}"))
    for _ in range(300):
        if len(got) == 9:
            break
        await drain(0.01)
    stamped = [d for d in got if TRACE_HEADER in d.properties.headers]
    assert len(stamped) == 3
    assert sum(d.trace is not None for d in got) == 3
    broker.close()


# ---- chaos schedules on the AMQP transport (ROADMAP PR 2 follow-up) --------

def _chaos_state(**kw):
    from matchmaking_tpu.config import ChaosConfig
    from matchmaking_tpu.utils.chaos import ChaosState

    return ChaosState(ChaosConfig(**kw))


@pytest.mark.asyncio
async def test_amqp_chaos_scripted_drop_and_dup():
    """The in-proc broker's scripted drop/dup semantics carried over the
    wire: the seq rides the x-chaos-seq header, a scripted first-attempt
    drop nack-requeues before the callback (redelivery makes progress),
    and a dup storm publishes extra copies with their own seqs."""
    from matchmaking_tpu.service.amqp_transport import CHAOS_SEQ_HEADER
    from matchmaking_tpu.utils.trace import EventLog

    broker, server = make_broker()
    broker.chaos = _chaos_state(seed=3, queues=("cq",), drop_seqs=(1,),
                                dup_seqs=((2, 2),))
    broker.events = EventLog(64)
    got = []

    async def on_delivery(d):
        got.append(d)
        broker.ack(tag, d.delivery_tag)

    broker.declare_queue("cq")
    tag = broker.basic_consume("cq", on_delivery)
    for i in range(3):  # seqs 0,1,2 (storm copies take 3,4)
        broker.publish("cq", f"m{i}".encode(),
                       Properties(reply_to="rq", correlation_id=f"c{i}"))
    # 0 once + 1 once (after one injected drop) + 2 three times = 5.
    for _ in range(400):
        if len(got) >= 5:
            break
        await drain(0.01)
    bodies = sorted(d.body for d in got)
    assert bodies == [b"m0", b"m1", b"m2", b"m2", b"m2"]
    assert broker.stats["dropped"] == 1
    assert broker.stats["duplicated"] == 2
    # The dropped delivery's redelivery is marked redelivered.
    m1 = [d for d in got if d.body == b"m1"]
    assert m1[0].redelivered
    # Storm copies carry their own seq identity (header survives the wire).
    seqs = sorted(int(d.properties.headers[CHAOS_SEQ_HEADER])
                  for d in got if d.body == b"m2")
    assert seqs == [2, 3, 4]
    kinds = [e["kind"] for e in broker.events.snapshot()]
    assert "chaos_drop" in kinds and "chaos_dup" in kinds
    broker.close()


@pytest.mark.asyncio
async def test_amqp_chaos_partition_pause_and_resume():
    """Scripted partition [pause_seq, resume_seq): the queue's consumer
    gates shut when the pause seq publishes (deliveries buffer broker-side;
    at-least-once holds) and reopens on the resume seq — with the
    partition_max_s failsafe bounding a mis-scripted schedule."""
    broker, server = make_broker()
    broker.chaos = _chaos_state(seed=4, queues=("pq",),
                                partitions=((1, 3),), partition_max_s=10.0)
    got = []

    async def on_delivery(d):
        got.append(d)
        broker.ack(tag, d.delivery_tag)

    broker.declare_queue("pq")
    tag = broker.basic_consume("pq", on_delivery)
    broker.publish("pq", b"a", Properties(reply_to="r", correlation_id="a"))
    for _ in range(200):
        if got:
            break
        await drain(0.01)
    broker.publish("pq", b"b", Properties(reply_to="r", correlation_id="b"))
    await drain(0.3)  # paused: b (and anything later) must NOT deliver
    assert [d.body for d in got] == [b"a"]
    assert broker.stats["partitions"] == 1
    broker.publish("pq", b"c", Properties(reply_to="r", correlation_id="c"))
    broker.publish("pq", b"d", Properties(reply_to="r", correlation_id="d"))
    for _ in range(400):  # seq 3 (d) resumes the gate
        if len(got) == 4:
            break
        await drain(0.01)
    assert sorted(d.body for d in got) == [b"a", b"b", b"c", b"d"]
    broker.close()


@pytest.mark.asyncio
async def test_publish_batch_one_channel_op():
    """ISSUE 9: publish_batch delivers a whole window of responses through
    ONE _with_channel op; items with reply_to (trace-stamped requests)
    fall back to the per-message publish() path."""
    broker, server = make_broker()
    broker.declare_queue("replies")
    broker.declare_queue("req")
    broker.publish_batch([
        ("replies", b"r1", Properties(correlation_id="c1")),
        ("replies", b"r2", Properties(correlation_id="c2")),
        ("req", b"q1", Properties(reply_to="replies", correlation_id="c3")),
    ])
    assert broker.stats["published"] == 3
    assert broker.queue_depth("replies") == 2
    assert broker.queue_depth("req") == 1
    got = await broker.get("replies", timeout=1.0)
    assert got.body == b"r1" and got.properties.correlation_id == "c1"
    # The reply_to item took the publish() path → trace header stamped.
    req = await broker.get("req", timeout=1.0)
    assert "x-trace-enqueue" in req.properties.headers
    broker.close()
