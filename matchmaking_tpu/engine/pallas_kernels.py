"""Pallas TPU kernel for the hot op: fused masked scoring + best-per-block.

Mirrors the XLA hot path (`kernels._candidates` / the fused scan in
`kernels._search_step`): for every request row, the best candidate within
each pool block of ``super_blk`` slots (= the engine's ``pool_block``, so the
candidate lists are IDENTICAL to the XLA path's — same block geometry, same
first-index tie preference). The score tile lives in VMEM and is reduced
immediately; nothing (B × blk)-shaped ever touches HBM:

    grid = (B / B_TILE, P / SUPER_BLK)    # pool-block axis innermost
    per cell: unrolled sub-tiles of ``sub_blk`` pool slots → score (VPU)
    → row max/argmax folded across sub-tiles (strict >, keeping the earlier
    index like jnp.argmax) → lane j of the running (B_TILE, 128) result in
    VMEM scratch; the last block writes the output.

Measured on v5e (round 2): the XLA fused scan and this kernel are within
noise of each other once both avoid materializing scores (the round-1 top-k
variants were 2-4× slower than either).

STATUS (settled round 4): this is a PINNED REFERENCE, not a production code
path. The former ``EngineConfig.use_pallas`` gate was removed — the Pallas
variant ran admission as a separate pool pass, which costs ~20 µs of HBM
traffic against a ~7.4 ms step (<1%), so even a perfectly fused Pallas step
cannot clear a ≥15% win over the XLA scan that already fuses
admit+score+best in one pass. tests/test_pallas.py keeps this kernel
exactly equivalent (same lists, same tie rule, interpret mode on CPU) so it
remains a working starting point for chips where hand tiling DOES win.

Layout notes (TPU tiling wants trailing-dim 128):
- pool fields pre-packed (7, P) f32: rating, rd, region, mode, threshold,
  enqueue_t, active — codes/flags are exact in f32.
- batch packed (B, 128) f32, first 7 columns: slot, rating, rd, region,
  mode, eff_threshold (widening pre-applied), valid.
- outputs (B, 128) f32 ×2 (vals, idx); callers slice [:, :n_blocks].

On non-TPU backends the pallas_call runs in interpret mode (tests), so CPU
correctness is pinned against the XLA path.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces (absent on some CPU-only builds)
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
    _SMEM = pltpu.SMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = _SMEM = None

_NEG_INF = -jnp.inf
LANES = 128  # output/pad width (TPU lane count) — caps n_blocks at 128

#: Row order of the packed pool input.
POOL_ROWS = ("rating", "rd", "region", "mode", "threshold", "enqueue_t",
             "active")


def _kernel(now_ref, pool_ref, batch_ref, out_v_ref, out_i_ref,
            best_v, best_i, *, super_blk: int, sub_blk: int, capacity: int,
            glicko2: bool, widen_per_sec: float, max_threshold: float,
            g_coeff: float):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        best_v[:] = jnp.full_like(best_v, _NEG_INF)
        best_i[:] = jnp.full_like(best_i, float(capacity))

    b = batch_ref[:]                      # (B_TILE, 128)
    q_slot = b[:, 0:1]
    q_rating = b[:, 1:2]
    q_rd = b[:, 2:3]
    q_reg = b[:, 3:4]
    q_mode = b[:, 4:5]
    q_thr_eff = b[:, 5:6]
    q_valid = b[:, 6:7]

    b_tile = b.shape[0]
    blk_v = jnp.full((b_tile,), _NEG_INF, jnp.float32)
    blk_i = jnp.full((b_tile,), float(capacity), jnp.float32)

    # Unrolled sub-tiles: the (B_TILE, sub_blk) score tile stays in VMEM and
    # is reduced immediately; the fold keeps the EARLIER index on exact ties
    # (strict >), matching jnp.argmax over the whole block.
    for s in range(super_blk // sub_blk):
        p = pool_ref[:, s * sub_blk:(s + 1) * sub_blk]   # (7, sub_blk)
        c_rating = p[0:1, :]
        c_rd = p[1:2, :]
        c_reg = p[2:3, :]
        c_mode = p[3:4, :]
        c_thr = p[4:5, :]
        c_enq = p[5:6, :]
        c_act = p[6:7, :]

        d = jnp.abs(q_rating - c_rating)  # (B_TILE, sub_blk)
        if glicko2:
            # EXACTLY scoring.glicko_g's expression (1/x**0.5, not rsqrt —
            # the approximate reciprocal sqrt diverges from the XLA path by
            # ulps, which breaks equivalence at threshold edges).
            rd2 = q_rd * q_rd + c_rd * c_rd
            d = d * (1.0 / (1.0 + g_coeff * rd2) ** 0.5)
        if widen_per_sec > 0.0:
            now = now_ref[0, 0]
            waited = jnp.maximum(0.0, now - c_enq)
            c_thr_eff = jnp.minimum(
                jnp.float32(max_threshold),
                c_thr + jnp.float32(widen_per_sec) * waited)
        else:
            c_thr_eff = c_thr
        limit = jnp.minimum(q_thr_eff, c_thr_eff)

        region_ok = (q_reg == 0.0) | (c_reg == 0.0) | (q_reg == c_reg)
        mode_ok = (q_mode == 0.0) | (c_mode == 0.0) | (q_mode == c_mode)
        # Mosaic: iota must be integer-typed; cast after.
        base = jnp.float32(j * super_blk + s * sub_blk)
        gidx = base + jax.lax.broadcasted_iota(
            jnp.int32, (1, sub_blk), 1).astype(jnp.float32)
        valid = ((c_act > 0.0) & (q_valid > 0.0) & region_ok & mode_ok
                 & (q_slot != gidx) & (d <= limit))
        scores = jnp.where(valid, -d, _NEG_INF)

        v = jnp.max(scores, axis=1)                       # (B_TILE,)
        a = jnp.argmax(scores, axis=1)                    # (B_TILE,)
        gi = base + a.astype(jnp.float32)
        take = v > blk_v
        blk_v = jnp.where(take, v, blk_v)
        blk_i = jnp.where(take & (v > _NEG_INF), gi, blk_i)

    # Deposit this block's best into lane j of the running result.
    lane = jax.lax.broadcasted_iota(jnp.int32, (b_tile, LANES), 1)
    onehot = lane == j
    best_v[:] = jnp.where(onehot, blk_v[:, None], best_v[:])
    best_i[:] = jnp.where(onehot, blk_i[:, None], best_i[:])

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        out_v_ref[:] = best_v[:]
        out_i_ref[:] = best_i[:]


@functools.partial(
    jax.jit,
    static_argnames=("super_blk", "sub_blk", "b_tile", "capacity", "glicko2",
                     "widen_per_sec", "max_threshold", "interpret"))
def pallas_block_best(pool_packed, batch_packed, now, *, super_blk: int,
                      sub_blk: int, b_tile: int, capacity: int, glicko2: bool,
                      widen_per_sec: float, max_threshold: float,
                      interpret: bool = False):
    """(pool f32[7,P], batch f32[B,128], now f32) → (vals f32[B,n_blocks],
    idx i32[B,n_blocks]) — best candidate per ``super_blk``-wide pool block,
    identical lists to the XLA ``kernels._candidates``."""
    _, pcap = pool_packed.shape
    b = batch_packed.shape[0]
    # b_tile must divide b (batch buckets are arbitrary ints — round-1
    # advisory fix: derive a divisor instead of asserting).
    b_tile = math.gcd(b, min(b_tile, b))
    sub_blk = min(sub_blk, super_blk)
    while super_blk % sub_blk != 0:
        sub_blk //= 2
    assert pcap % super_blk == 0
    n_blocks = pcap // super_blk
    assert n_blocks <= LANES, (
        f"{n_blocks} pool blocks exceed the {LANES}-lane result tile; "
        f"raise pool_block")
    q = math.log(10.0) / 400.0
    g_coeff = 3.0 * q * q / (math.pi * math.pi)

    kernel = functools.partial(
        _kernel, super_blk=super_blk, sub_blk=sub_blk, capacity=capacity,
        glicko2=glicko2, widen_per_sec=widen_per_sec,
        max_threshold=max_threshold, g_coeff=g_coeff)
    mem = {} if pltpu is None else {"memory_space": _VMEM}
    smem = {} if pltpu is None else {"memory_space": _SMEM}
    scratch = (
        [jax.ShapeDtypeStruct((b_tile, LANES), jnp.float32)] * 2
        if pltpu is None else
        [_VMEM((b_tile, LANES), jnp.float32),
         _VMEM((b_tile, LANES), jnp.float32)]
    )
    out_v, out_i = pl.pallas_call(
        kernel,
        grid=(b // b_tile, n_blocks),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0), **smem),
            pl.BlockSpec((len(POOL_ROWS), super_blk), lambda i, j: (0, j), **mem),
            pl.BlockSpec((b_tile, LANES), lambda i, j: (i, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((b_tile, LANES), lambda i, j: (i, 0), **mem),
            pl.BlockSpec((b_tile, LANES), lambda i, j: (i, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, LANES), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(jnp.asarray(now, jnp.float32).reshape(1, 1), pool_packed, batch_packed)
    return out_v[:, :n_blocks], out_i[:, :n_blocks].astype(jnp.int32)


def pack_pool_rows(pool: dict) -> jnp.ndarray:
    """Pool dict → (7, P) f32 (active as 0/1)."""
    return jnp.stack([pool[f].astype(jnp.float32) for f in POOL_ROWS])


def pack_batch_rows(batch: dict, q_thr_eff) -> jnp.ndarray:
    """Batch dict (+ pre-widened query thresholds) → (B, 128) f32."""
    cols = jnp.stack([
        batch["slot"].astype(jnp.float32),
        batch["rating"],
        batch["rd"],
        batch["region"].astype(jnp.float32),
        batch["mode"].astype(jnp.float32),
        q_thr_eff,
        batch["valid"].astype(jnp.float32),
    ], axis=1)                                        # (B, 7)
    b = cols.shape[0]
    return jnp.concatenate(
        [cols, jnp.zeros((b, LANES - cols.shape[1]), jnp.float32)], axis=1)
