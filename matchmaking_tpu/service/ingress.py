"""In-process sharded ingress: consume-burst decode + per-shard state.

ISSUE 12's ingress plane. PR 9 made the request→response path
window-granular on the EGRESS side; the top remaining per-delivery cost
(PR 6 attribution) was the broker consume machinery — one handler
invocation + bookkeeping per delivery — and the flush-time re-decode of
bodies the consumer had already held in its hands. This module is the
decode side of that story:

- **Consume-burst decode** — the broker's ``consume_batch`` seam hands the
  app ONE callback per drained burst; ``IngressShards.decode_burst`` packs
  the burst's bodies into a single arena + offset array (the mirror of the
  batch ENCODERS' output layout) and decodes them in one native call
  (``codec.decode_batch_concat``). Each delivery gets a ``(DecodedBurst,
  index)`` reference (``Delivery.row``), so the window flush assembles its
  columns by vectorized gather instead of re-decoding — the columns merge
  at the EDF cut, whichever bursts and shards they came from.

- **Shard workers** — rows the native decoder flags NEEDS_PYTHON (parties,
  escapes, exotica) fall back through ``contract.decode_request`` (the
  semantic source of truth), consistent-hashed by correlation id (the
  request identity available pre-decode) into
  ``BrokerConfig.ingress_shards`` worker slices. At N=1 the fallback runs
  inline (today's path, byte for byte); at N>1 each shard's slice runs on
  a worker thread — disjoint row indices, so the writes into the burst
  arrays never contend, and the workers touch NO shared mutable state
  (the dedup probe runs at the cut, on the event loop).

- **Per-shard settlement state** — ``ShardedRecent`` splits the
  at-least-once terminal-replay cache into per-shard dicts keyed by
  player id, so the cut-time probe (and any future shard-local prober)
  only ever touches one shard's dict per row. Everything else on the
  ingress path (admission credits, the batcher, trace settles) stays
  event-loop-confined and is proven settle-exactly-once by matchlint's
  settlement typestate — which is exactly why this split can stay
  lock-free.

Region/game-mode names are deliberately kept as STRINGS in the burst
columns and interned at the cut: interner codes belong to one engine
incarnation, and a crash revive or breaker swap between consume and flush
would otherwise dereference stale codes.
"""

from __future__ import annotations

import asyncio
import zlib
from typing import TYPE_CHECKING, Any  # noqa: F401  (Any: reject tuples)

import numpy as np

if TYPE_CHECKING:  # pragma: no cover - typing only
    from matchmaking_tpu.service.broker import Delivery


def shard_of(key: str, n: int) -> int:
    """Consistent request-id → shard hash. crc32, NOT ``hash()``: the
    builtin is salted per process (PYTHONHASHSEED), and the equivalence
    soaks replay shard routing bit-identically across runs."""
    if n <= 1:
        return 0
    return zlib.crc32(key.encode()) % n


class ShardedRecent:
    """The at-least-once terminal-replay cache (player id → (encoded body,
    expiry)), split into per-shard dicts by the consistent request-id hash.
    N=1 is a single dict — the pre-shard behavior exactly. All mutation
    happens on the event loop (probe at the cut, ``_remember`` at publish);
    the split means a future shard worker probing ITS shard can never
    contend with another's."""

    __slots__ = ("n", "_shards")

    def __init__(self, n: int = 1):
        self.n = max(1, int(n))
        self._shards: list[dict[str, tuple[bytes, float]]] = [
            {} for _ in range(self.n)]

    def _dict(self, pid: str) -> dict[str, tuple[bytes, float]]:
        if self.n == 1:
            return self._shards[0]
        return self._shards[zlib.crc32(pid.encode()) % self.n]

    def get(self, pid: str) -> "tuple[bytes, float] | None":
        return self._dict(pid).get(pid)

    def pop(self, pid: str) -> None:
        self._dict(pid).pop(pid, None)

    def set(self, pid: str, value: "tuple[bytes, float]") -> None:
        self._dict(pid)[pid] = value

    def __len__(self) -> int:
        if self.n == 1:
            return len(self._shards[0])
        return sum(len(d) for d in self._shards)

    def items(self):
        """Every (pid, (body, expiry)) across the shards — the journal
        compaction's carry walk (ISSUE 15): live dedup entries are
        re-appended into the fresh segment so the at-least-once horizon
        survives the truncation. Shard-major order (deterministic: the
        shard split is a pure hash of the id)."""
        for d in self._shards:
            yield from d.items()

    def prune(self, now: float) -> None:
        """Drop expired entries (the time-throttled flush-side prune)."""
        for i, d in enumerate(self._shards):
            self._shards[i] = {k: v for k, v in d.items() if v[1] > now}


class DecodedBurst:
    """One consume burst's preparsed request columns. Row i of the burst
    is valid iff ``ok[i]`` (invalid rows were settled at consume).
    Region/mode are names ("" = wildcard), interned at the EDF cut.

    The all-OK fast path ADOPTS the native decoder's output arrays
    directly (zero copies — the common shape under load); bursts with
    fallback/reject rows allocate and fill."""

    __slots__ = ("ids", "rating", "rd", "threshold", "region", "mode", "ok")

    def __init__(self, ids, rating, rd, threshold, region, mode, ok):
        self.ids = ids
        self.rating = rating
        self.rd = rd
        self.threshold = threshold
        self.region = region
        self.mode = mode
        self.ok = ok

    @classmethod
    def empty(cls, n: int) -> "DecodedBurst":
        return cls(np.empty(n, object), np.empty(n, np.float32),
                   np.empty(n, np.float32), np.empty(n, np.float32),
                   np.empty(n, object), np.empty(n, object),
                   np.zeros(n, bool))


class IngressShards:
    """N in-process ingress shard workers for one queue runtime."""

    def __init__(self, n: int = 1):
        self.n = max(1, int(n))

    # The NEEDS_PYTHON fallback for one shard's slice: decode through the
    # contract path, write fields into the burst arrays (disjoint indices
    # per shard — thread-safe by construction), collect rejects. The
    # (counter, code, reason) rows MUST keep the flush's classification
    # (ContractError → rejected_by_middleware with its own code/reason;
    # party > 1 → rejected_by_engine/party_not_supported): the caller
    # settles them through app._reject_delivery, and the on/off
    # equivalence soaks pin the mapping.
    @staticmethod
    def _fallback_slice(burst: DecodedBurst, deliveries: "list[Delivery]",
                        idxs: "list[int]") -> "list[tuple[int, str, str, str]]":
        from matchmaking_tpu.service.contract import (
            ContractError,
            decode_request,
        )

        rejects: list[tuple[int, str, str, str]] = []
        for i in idxs:
            try:
                req = decode_request(deliveries[i].body)
            except ContractError as e:
                rejects.append((i, "rejected_by_middleware", e.code,
                                e.reason))
                continue
            if req.party_size > 1:
                # 1v1 queue: parties are unservable (oracle semantics) —
                # same reject the flush's fallback path produced.
                rejects.append((i, "rejected_by_engine",
                                "party_not_supported",
                                "engine rejected request: "
                                "party_not_supported"))
                continue
            burst.ids[i] = req.id
            burst.rating[i] = req.rating
            burst.rd[i] = req.rating_deviation
            burst.threshold[i] = (np.nan if req.rating_threshold is None
                                  else req.rating_threshold)
            burst.region[i] = "" if req.region == "*" else req.region
            burst.mode[i] = "" if req.game_mode == "*" else req.game_mode
            burst.ok[i] = True
        return rejects

    async def decode_burst(
        self, deliveries: "list[Delivery]",
    ) -> "tuple[list[Delivery], list[tuple[Any, str, str, str]]]":
        """Decode one consume burst: one native call over the burst's
        concatenated bodies, per-shard contract fallback for NEEDS_PYTHON
        rows. Sets ``d.row = (burst, i)`` on every valid delivery.
        Returns (kept deliveries, rejects) — the CALLER settles rejects
        (respond + ack) so all settlement stays in the runtime."""
        from matchmaking_tpu.native import codec

        n = len(deliveries)
        # No per-body copy: Delivery.body is bytes on both transports, and
        # join() materializes the one concatenated buffer the decoder
        # reads (the mirror of the encoders' arena layout).
        bodies = [d.body if isinstance(d.body, bytes) else bytes(d.body)
                  for d in deliveries]
        offsets = np.zeros(n + 1, np.int64)
        np.cumsum(np.fromiter((len(b) for b in bodies), np.int64, n),
                  out=offsets[1:])
        native = codec.decode_batch_concat(b"".join(bodies), offsets)
        rejects_i: list[tuple[int, str, str, str]] = []
        fallback: list[int] = []
        if native is None:
            # Native library raced away: the whole burst takes the
            # contract path (sharded below).
            burst = DecodedBurst.empty(n)
            fallback = list(range(n))
        else:
            ids_n, rating_n, rd_n, thr_n, reg_n, mode_n, status_n = native
            if not status_n.any():  # every row OK (== codec.OK == 0)
                # The loaded-path common case: adopt the decoder's arrays
                # wholesale — no per-row status walk, no column copies.
                burst = DecodedBurst(ids_n, rating_n, rd_n, thr_n,
                                     reg_n, mode_n, np.ones(n, bool))
                for i, d in enumerate(deliveries):
                    d.row = (burst, i)
                return deliveries, []
            burst = DecodedBurst.empty(n)
            status_l = status_n.tolist()
            for i in range(n):
                st = status_l[i]
                if st == codec.OK:
                    burst.ok[i] = True
                elif st == codec.NEEDS_PYTHON:
                    fallback.append(i)
                else:
                    rejects_i.append((i, "rejected_by_middleware",
                                      codec.error_code(st),
                                      "malformed payload"))
            okm = burst.ok
            burst.ids[okm] = ids_n[okm]
            burst.rating[okm] = rating_n[okm]
            burst.rd[okm] = rd_n[okm]
            burst.threshold[okm] = thr_n[okm]
            burst.region[okm] = reg_n[okm]
            burst.mode[okm] = mode_n[okm]
        if fallback:
            if self.n > 1 and len(fallback) > 1:
                # Shard the contract-path work by request id where we have
                # one (correlation id pre-decode — stable across
                # redelivery), one worker thread per non-empty shard.
                by_shard: list[list[int]] = [[] for _ in range(self.n)]
                for i in fallback:
                    key = deliveries[i].properties.correlation_id or str(i)
                    by_shard[shard_of(key, self.n)].append(i)
                slices = [idxs for idxs in by_shard if idxs]
                results = await asyncio.gather(*(
                    asyncio.to_thread(self._fallback_slice, burst,
                                      deliveries, idxs)
                    for idxs in slices))
                for rej in results:
                    rejects_i.extend(rej)
            else:
                rejects_i.extend(
                    self._fallback_slice(burst, deliveries, fallback))
        kept: list[Delivery] = []
        ok_l = burst.ok.tolist()
        for i, d in enumerate(deliveries):
            if ok_l[i]:
                d.row = (burst, i)
                kept.append(d)
        rejects = [(deliveries[i], counter, code, reason)
                   for i, counter, code, reason in rejects_i]
        return kept, rejects


def gather_rows(refs: "list[tuple[DecodedBurst, int]]"):
    """Merge window rows from their burst columns at the EDF cut: one
    vectorized take per (burst, column) instead of a per-row Python loop.
    ``refs`` is in final window order (post EDF sort / dedup / expiry
    filtering); rows from the same burst gather together and scatter back
    into their window positions."""
    k = len(refs)
    if k and all(burst is refs[0][0] for burst, _ in refs):
        # Single-burst window (bursts ≥ windows under load): one fancy
        # index per column, no scatter bookkeeping.
        b = refs[0][0]
        idx = np.fromiter((i for _, i in refs), np.int64, k)
        return (b.ids[idx], b.rating[idx], b.rd[idx], b.threshold[idx],
                b.region[idx], b.mode[idx])
    ids = np.empty(k, object)
    rating = np.empty(k, np.float32)
    rd = np.empty(k, np.float32)
    threshold = np.empty(k, np.float32)
    region = np.empty(k, object)
    mode = np.empty(k, object)
    by_burst: dict[int, tuple[DecodedBurst, list[int], list[int]]] = {}
    for j, (burst, i) in enumerate(refs):
        entry = by_burst.get(id(burst))
        if entry is None:
            entry = by_burst[id(burst)] = (burst, [], [])
        entry[1].append(i)
        entry[2].append(j)
    for burst, src, dst in by_burst.values():
        s = np.asarray(src, np.int64)
        t = np.asarray(dst, np.int64)
        ids[t] = burst.ids[s]
        rating[t] = burst.rating[s]
        rd[t] = burst.rd[s]
        threshold[t] = burst.threshold[s]
        region[t] = burst.region[s]
        mode[t] = burst.mode[s]
    return ids, rating, rd, threshold, region, mode
