"""Small-scope interleaving model checker (ISSUE 19): the explorer's
generic machinery on a toy world, and the protocol world end-to-end —
clean exhaustive runs on the real objects, every seeded mutant caught
with a minimized digest-replayable counterexample.

The fast scopes here are tier-1; the committed CI smoke scope (depth 6)
runs in scripts/check.sh via ``bench.py --modelcheck``.
"""

import dataclasses

import pytest

from matchmaking_tpu.analysis.modelcheck import (
    MUTANTS, ModelCheckConfig, mutation_gate_config, run_modelcheck,
    run_mutation_gate)
from matchmaking_tpu.testing.scheduler import Explorer, schedule_digest

pytestmark = pytest.mark.protocol


# ---- the generic explorer on a toy world -----------------------------------

class _CounterWorld:
    """Two independent counters ('a', 'b'), each incrementable to 3; the
    world is 'violated' when counter a reaches a configured trip value
    AFTER a longer decoy prefix — exercises minimization."""

    ACTIONS = ("inc@a", "inc@b")

    def __init__(self, trip_at=None):
        self.vals = {"a": 0, "b": 0}
        self.trip_at = trip_at

    def enabled(self):
        return [k for k in self.ACTIONS
                if self.vals[k.partition("@")[2]] < 3]

    def step(self, key):
        slot = key.partition("@")[2]
        self.vals[slot] += 1
        return f"{slot} -> {self.vals[slot]}"

    def check(self):
        if self.trip_at is not None and self.vals["a"] >= self.trip_at:
            return f"counter a reached {self.vals['a']}"
        return None

    def digest(self):
        return (self.vals["a"], self.vals["b"])

    def slot(self, key):
        return key.partition("@")[2]

    def index(self, key):
        return self.ACTIONS.index(key)

    def close(self):
        pass


def test_explorer_enumerates_exhaustively_with_dedup_and_por():
    ex = Explorer(_CounterWorld, max_depth=6)
    res = ex.explore()
    assert res.violation is None
    assert res.exhaustive
    # The reachable state space is exactly the 4x4 counter grid.
    assert res.states == 16
    assert res.pruned_por > 0


def test_explorer_por_preserves_the_reachable_state_space():
    full = Explorer(_CounterWorld, max_depth=6, por=False).explore()
    reduced = Explorer(_CounterWorld, max_depth=6, por=True).explore()
    assert full.exhaustive and reduced.exhaustive
    assert full.states == reduced.states
    assert reduced.nodes < full.nodes


def test_explorer_minimizes_to_the_shortest_failing_schedule():
    ex = Explorer(lambda: _CounterWorld(trip_at=2), max_depth=6)
    res = ex.explore()
    assert res.violation == "counter a reached 2"
    # Decoy inc@b steps are minimized away: two a-increments suffice.
    assert res.schedule == ["inc@a", "inc@a"]
    assert len(res.timeline) == 3 and "VIOLATION" in res.timeline[-1]
    assert res.digest == ""  # digest is the caller's (scope-salted) job


def test_schedule_digest_is_scope_salted():
    sched = ["inc@a", "inc@a"]
    assert (schedule_digest(sched, {"depth": 4})
            != schedule_digest(sched, {"depth": 5}))
    assert (schedule_digest(sched, {"depth": 4})
            == schedule_digest(list(sched), {"depth": 4}))


# ---- the protocol world on the real objects --------------------------------

def _small(**over):
    base = ModelCheckConfig(queues=1, depth=4, admits=2, settles=1,
                            faults=("expire", "drop"), fault_budget=2)
    return dataclasses.replace(base, **over)


def test_protocol_clean_at_single_queue_scope():
    rep = run_modelcheck(_small())
    assert rep["modelcheck_violations"] == 0
    assert rep["modelcheck_exhaustive"]
    assert rep["modelcheck_states_explored"] > 50


def test_protocol_clean_at_two_queue_scope_with_crash_and_dup():
    rep = run_modelcheck(ModelCheckConfig(
        queues=2, depth=4, faults=("expire", "crash", "drop", "dup"),
        fault_budget=2))
    assert rep["modelcheck_violations"] == 0
    assert rep["modelcheck_exhaustive"]
    # Two queues share one authority; POR must still fire across them.
    assert rep["modelcheck_pruned_por"] > 0


def test_stale_epoch_resume_is_refused_not_violating():
    """The fenced ex-primary resuming WITHOUT a crash (expire ->
    takeover -> admit/publish) must be refused by the fences — replaying
    that exact schedule shows refusals and no violation."""
    rep = run_modelcheck(
        _small(settles=1),
        replay=["settle@q0", "expire@q0", "takeover@q0", "admit@q0",
                "publish@q0"])
    assert rep["modelcheck_violations"] == 0
    timeline = "\n".join(rep["modelcheck_timeline"])
    assert "admit refused: journal append fenced" in timeline
    assert "publish q0-t1 refused: epoch superseded" in timeline


@pytest.mark.parametrize("mutant", MUTANTS)
def test_every_seeded_mutant_yields_a_minimized_counterexample(mutant):
    cfg = dataclasses.replace(mutation_gate_config(), mutation=mutant)
    rep = run_modelcheck(cfg)
    assert rep["modelcheck_violations"] == 1
    assert 1 <= len(rep["modelcheck_schedule"]) <= cfg.depth
    assert rep["modelcheck_schedule_digest"]
    # The counterexample replays bit-identically from its schedule.
    rerun = run_modelcheck(cfg, replay=rep["modelcheck_schedule"])
    assert rerun["modelcheck_violation"] == rep["modelcheck_violation"]
    assert (rerun["modelcheck_schedule_digest"]
            == rep["modelcheck_schedule_digest"])


def test_mutation_gate_passes_and_reports_per_mutant_evidence():
    gate = run_mutation_gate()
    assert gate["mutation_gate_passed"]
    assert gate["mutation_gate_baseline_clean"]
    assert set(gate["mutation_gate_mutants"]) == set(MUTANTS)
    for rec in gate["mutation_gate_mutants"].values():
        assert rec["caught"] and rec["replay_ok"]
        assert rec["timeline"][-1].startswith("VIOLATION")


def test_counterexample_timeline_reads_as_a_causal_spine():
    cfg = dataclasses.replace(mutation_gate_config(),
                              mutation="skip-append-fence")
    rep = run_modelcheck(cfg)
    tl = rep["modelcheck_timeline"]
    assert tl[0].startswith("step 1:")
    assert any("lease expired" in ln for ln in tl)
    assert any("took over" in ln for ln in tl)
    assert "ex-primary produced an externally visible effect" in tl[-1]
