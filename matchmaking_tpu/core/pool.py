"""The player pool: a structure-of-arrays resident in device HBM.

This is the TPU-native replacement for the reference's ETS table (SURVEY.md
§2 C8): where the reference keeps queued players as rows in an in-memory BEAM
table scanned per request, we keep them as fixed-capacity parallel arrays in
HBM so a whole request window scores against every waiting player in one
vectorized kernel.

Design (SURVEY.md §7 step 1):

- **Fixed capacity P, static shapes.** Slots are recycled through a host-side
  free list; XLA never sees a dynamic pool size (recompile-free hot path).
- **Single-writer slot allocator on the host** (SURVEY.md §5 "Race
  detection"): all admissions/evictions flow through one `PlayerPool` object;
  the device arrays are updated only by the jitted step functions it calls.
- **Authoritative host mirror, columnar.** The host keeps every waiting
  request as parallel numpy columns (slot-indexed). Device state is a pure
  function of the mirror, which makes the mirror the checkpoint: on sidecar
  death, re-admit the mirror (SURVEY.md §5 "Checkpoint/resume").
  `SearchRequest` objects are materialized lazily (only for matched slots
  that need response objects) — the object layer costs ~10-20 µs/request,
  which would dwarf the ~1 ms device kernel at 10^5 requests/sec.
- **Vectorized free list**: a numpy stack with a head cursor; allocating a
  window is one slice, releasing is one slice store — no per-request Python.
- **String interning.** Wire-level region/game-mode strings are interned to
  int32 codes (0 = wildcard) so filter masks are integer compares on device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from matchmaking_tpu.service.contract import ANY, RequestColumns, SearchRequest

# Field definitions for the device SoA. Kept in one place so the kernels, the
# pool, and the sharded engine agree on array layout.
POOL_FIELDS: tuple[tuple[str, np.dtype], ...] = (
    ("rating", np.float32),
    ("rd", np.float32),          # Glicko-2 rating deviation
    ("region", np.int32),        # interned; 0 = ANY
    ("mode", np.int32),          # interned; 0 = ANY
    ("threshold", np.float32),   # base rating_threshold for this player
    ("enqueue_t", np.float32),   # seconds; widening input
    ("active", np.bool_),
)


class Interner:
    """str → dense int32 codes; code 0 is reserved for the ANY wildcard."""

    def __init__(self) -> None:
        self._codes: dict[str, int] = {ANY: 0}
        self._names: list[str] = [ANY]

    def code(self, name: str) -> int:
        c = self._codes.get(name)
        if c is None:
            c = len(self._names)
            self._codes[name] = c
            self._names.append(name)
        return c

    def name(self, code: int) -> str:
        return self._names[code]


class PoolFullError(RuntimeError):
    pass


@dataclass
class BatchArrays:
    """A padded request window, ready for the device (host numpy; the engine
    moves it with the step call). ``valid`` masks padding lanes."""

    slot: np.ndarray      # i32[B] — pre-allocated pool slot per request
    rating: np.ndarray    # f32[B]
    rd: np.ndarray        # f32[B]
    region: np.ndarray    # i32[B]
    mode: np.ndarray      # i32[B]
    threshold: np.ndarray # f32[B]
    enqueue_t: np.ndarray # f32[B]
    valid: np.ndarray     # bool[B]


class PlayerPool:
    """Host-side owner of the pool: slot allocator + authoritative mirror.

    The device arrays themselves live with the engine (they are jitted-step
    carry state); this class owns which slot means which player.
    """

    def __init__(self, capacity: int, default_threshold: float,
                 band_edges: Sequence[float] | None = None,
                 segments: int = 0):
        self.capacity = int(capacity)
        self.default_threshold = float(default_threshold)
        #: Incremental per-SEGMENT occupancy (ISSUE 14): the engine passes
        #: ``segments`` = its device block count, and every allocate/release
        #: keeps a per-block occupancy histogram by SLOT RANGE — the host
        #: twin of the device bucket index's counts, and the O(segments)
        #: gate the sharded bucket-frontier step checks per window (max
        #: per-bucket occupancy must fit the frontier K). 0 = untracked.
        self._segments = max(0, int(segments))
        self._seg_size = (self.capacity // self._segments
                          if self._segments else 0)
        self._seg_n = (np.zeros(self._segments, np.int64)
                       if self._segments else None)
        # Vectorized free list: pop from the END (head), so initial pops
        # yield slot 0, 1, 2, ... (kept for slot-order determinism in tests).
        self._free = np.arange(self.capacity - 1, -1, -1, dtype=np.int32)
        self._head = self.capacity  # number of free slots
        # Rating-banded mode: slots partitioned into contiguous bands, one
        # free stack per band; a player's slot comes from the band holding
        # its rating (spilling outward to the nearest non-full band). Keeps
        # each pool BLOCK's live rating interval narrow, which is what makes
        # the kernels' bit-exact block pruning effective (kernels.py
        # "_search_step_pruned"). ``band_edges`` are the len(R-1) ascending
        # rating boundaries; band b owns slots [b·P/R, (b+1)·P/R).
        self._band_edges: np.ndarray | None = None
        if band_edges is not None and len(band_edges) > 0:
            edges = np.asarray(band_edges, np.float64)
            if not np.all(np.diff(edges) > 0):
                raise ValueError("band_edges must be strictly ascending")
            r = edges.size + 1
            self._band_edges = edges
            self._band_start = np.array(
                [b * self.capacity // r for b in range(r + 1)], np.int64)
            # Stacks store descending so pops yield ascending slot order.
            self._band_free = [
                np.arange(self._band_start[b + 1] - 1,
                          self._band_start[b] - 1, -1, dtype=np.int32)
                for b in range(r)
            ]
            self._band_head = np.array(
                [s.size for s in self._band_free], np.int64)
        self._slot_of: dict[str, int] = {}                   # player id → slot
        # Columnar mirror (slot-indexed).
        self.m_id = np.full(self.capacity, None, dtype=object)
        self.m_rating = np.zeros(self.capacity, np.float32)
        self.m_rd = np.zeros(self.capacity, np.float32)
        self.m_region = np.zeros(self.capacity, np.int32)
        self.m_mode = np.zeros(self.capacity, np.int32)
        self.m_threshold = np.zeros(self.capacity, np.float32)  # resolved (no NaN)
        self.m_thr_override = np.zeros(self.capacity, np.bool_)
        self.m_enqueued = np.zeros(self.capacity, np.float64)
        self.m_reply = np.full(self.capacity, "", dtype=object)
        self.m_corr = np.full(self.capacity, "", dtype=object)
        #: QoS priority tier per slot (service/overload.py; 0 = untiered
        #: default) and absolute x-deadline per slot (wall-clock seconds;
        #: 0.0 = none). Host-mirror-only columns — the device kernels never
        #: see them: priority ordering happens at admission/window-cut time
        #: and expiry is a host sweep + batched device eviction.
        self.m_tier = np.zeros(self.capacity, np.int32)
        self.m_deadline = np.zeros(self.capacity, np.float64)
        #: Incremental per-tier occupancy counts (tier → waiting players):
        #: admission's partition check reads this per delivery, and an
        #: O(pool) bincount per delivery would put a 100k scan on the
        #: ingress hot path.
        self._tier_n: dict[int, int] = {}
        #: Waiting players carrying a nonzero deadline — the O(1) gate the
        #: sweep loop checks per tick so deadline-less traffic never pays
        #: a pipeline drain for an empty sweep.
        self._deadline_n = 0
        # Declared role sets (config #5 device path); None for the columnar
        # 1v1 ingress, which never carries roles.
        self.m_roles = np.full(self.capacity, None, dtype=object)
        self.regions = Interner()
        self.modes = Interner()

    # ---- introspection ----------------------------------------------------

    def __len__(self) -> int:
        return len(self._slot_of)

    def free_count(self) -> int:
        return self._head

    def __contains__(self, player_id: str) -> bool:
        return player_id in self._slot_of

    def slot_of(self, player_id: str) -> int | None:
        return self._slot_of.get(player_id)

    def request_at(self, slot: int) -> SearchRequest:
        """Materialize the SearchRequest for one occupied slot (lazy — the
        mirror is columnar; objects are built only where needed)."""
        return SearchRequest(
            id=self.m_id[slot],
            rating=float(self.m_rating[slot]),
            rating_deviation=float(self.m_rd[slot]),
            game_mode=self.modes.name(int(self.m_mode[slot])),
            region=self.regions.name(int(self.m_region[slot])),
            rating_threshold=(float(self.m_threshold[slot])
                              if self.m_thr_override[slot] else None),
            roles=tuple(self.m_roles[slot] or ()),
            reply_to=self.m_reply[slot],
            correlation_id=self.m_corr[slot],
            enqueued_at=float(self.m_enqueued[slot]),
            tier=int(self.m_tier[slot]),
            deadline_at=float(self.m_deadline[slot]),
        )

    def waiting(self) -> list[SearchRequest]:
        """Checkpoint payload: every waiting request (insertion-time data)."""
        return [self.request_at(s) for s in self._slot_of.values()]

    def waiting_slots(self) -> np.ndarray:
        return np.fromiter(self._slot_of.values(), np.int32, len(self._slot_of))

    def deadline_count(self) -> int:
        """Waiting players with a stamped deadline (O(1); incremental)."""
        return self._deadline_n

    def segment_counts(self) -> "np.ndarray | None":
        """Per-segment (= device pool block / rating bucket) occupancy,
        maintained incrementally by allocate/release — O(segments) read,
        never a pool scan. None when segment tracking is off."""
        return self._seg_n

    def segment_max(self) -> int:
        """Peak per-segment occupancy (the sharded bucket-frontier gate's
        one number). 0 when untracked or empty."""
        if self._seg_n is None:
            return 0
        return int(self._seg_n.max(initial=0))

    def _seg_add(self, slots: np.ndarray, sign: int) -> None:
        if self._seg_n is None or slots.size == 0:
            return
        seg = np.minimum(slots // self._seg_size, self._segments - 1)
        np.add.at(self._seg_n, seg, sign)

    def band_report(self) -> "dict | None":
        """Host allocator state of the rating-banded free lists (ISSUE 14
        'free-slot heads'): per-band free-slot head positions + band sizes.
        None when banding is off."""
        if self._band_edges is None:
            return None
        return {
            "bands": len(self._band_free),
            "free_heads": [int(h) for h in self._band_head],
            "band_sizes": [int(self._band_start[b + 1] - self._band_start[b])
                           for b in range(len(self._band_free))],
            "edges": [float(e) for e in self._band_edges],
        }

    def tier_counts(self, n_tiers: int) -> list[int]:
        """Waiting players per QoS tier (len ``n_tiers``; out-of-range
        tiers are clamped into the last bucket). O(n_tiers) — maintained
        incrementally by allocate/release, never scanned."""
        out = [0] * max(1, n_tiers)
        for t, n in self._tier_n.items():
            out[min(max(t, 0), len(out) - 1)] += n
        return out

    # ---- mutation (single writer) -----------------------------------------

    def allocate_columns(self, cols: RequestColumns) -> np.ndarray:
        """Assign slots to a columnar window and record it in the mirror.
        All stores are vectorized; the id checks and the id→slot dict update
        are the only per-row work (~50 ns/id).

        Ids must be unique within the window and absent from the pool
        (engines dedupe before allocating); violations raise BEFORE any
        mutation, so the pool state is never half-updated."""
        n = len(cols)
        if n > self._head:
            raise PoolFullError(
                f"pool exhausted: {n} requested, {self._head} free "
                f"(capacity {self.capacity})"
            )
        ids = cols.ids.tolist()
        if len(set(ids)) != n:
            raise ValueError("duplicate player id in window")
        if any(pid in self._slot_of for pid in ids):
            raise ValueError("player already in pool")
        if self._band_edges is not None:
            slots = self._take_banded(np.asarray(cols.rating, np.float64), n)
        else:
            slots = self._free[self._head - n:self._head][::-1].copy()
        self._head -= n
        self.m_id[slots] = cols.ids
        self.m_rating[slots] = cols.rating
        self.m_rd[slots] = cols.rd
        self.m_region[slots] = cols.region
        self.m_mode[slots] = cols.mode
        override = ~np.isnan(cols.threshold)
        self.m_thr_override[slots] = override
        self.m_threshold[slots] = np.where(override, cols.threshold,
                                           self.default_threshold)
        self.m_enqueued[slots] = cols.enqueued_at
        # Missing transport columns must CLEAR the slots (a recycled slot
        # would otherwise leak the previous occupant's reply queue and route
        # a response to an unrelated player).
        self.m_reply[slots] = "" if cols.reply_to is None else cols.reply_to
        self.m_corr[slots] = ("" if cols.correlation_id is None
                              else cols.correlation_id)
        # QoS columns: unconditional stores (missing columns must clear a
        # recycled slot, or a stale tier/deadline would misclassify the
        # new occupant) + the incremental per-tier occupancy counts.
        if cols.tier is None:
            self.m_tier[slots] = 0
            self._tier_n[0] = self._tier_n.get(0, 0) + n
        else:
            self.m_tier[slots] = cols.tier
            for t, c in zip(*np.unique(np.asarray(cols.tier, np.int64),
                                       return_counts=True)):
                self._tier_n[int(t)] = self._tier_n.get(int(t), 0) + int(c)
        if cols.deadline is None:
            self.m_deadline[slots] = 0.0
        else:
            dl = np.nan_to_num(np.asarray(cols.deadline, np.float64),
                               nan=0.0)
            self.m_deadline[slots] = dl
            self._deadline_n += int((dl != 0.0).sum())
        self._slot_of.update(zip(ids, slots.tolist()))
        self._seg_add(slots, 1)
        return slots

    def allocate(self, requests: Sequence[SearchRequest]) -> list[int]:
        """Object-path compatibility wrapper around allocate_columns."""
        for req in requests:
            if req.id in self._slot_of:
                raise ValueError(f"player {req.id!r} already in pool")
        cols = RequestColumns.from_requests(
            requests, self.regions.code, self.modes.code)
        slots = self.allocate_columns(cols).tolist()
        for s, req in zip(slots, requests):
            if req.roles:
                self.m_roles[s] = req.roles
        return slots

    def release(self, slots: Sequence[int] | np.ndarray) -> None:
        """Evict slots (matched / cancelled / timed out) from the mirror."""
        arr = np.unique(np.asarray(slots, dtype=np.int32))
        if arr.size == 0:
            return
        # np.unique guards intra-call duplicate slots; the occupancy mask
        # guards cross-call double-release (idempotent like a dict mirror).
        ids = self.m_id[arr]
        occupied = np.fromiter((i is not None for i in ids), bool, arr.size)
        arr = arr[occupied]
        if arr.size == 0:
            return
        for pid in ids[occupied].tolist():
            del self._slot_of[pid]
        self._seg_add(arr, -1)
        # Per-tier/deadline occupancy bookkeeping BEFORE clearing slots.
        for t, c in zip(*np.unique(self.m_tier[arr], return_counts=True)):
            self._tier_n[int(t)] = self._tier_n.get(int(t), 0) - int(c)
        self._deadline_n -= int((self.m_deadline[arr] != 0.0).sum())
        self.m_deadline[arr] = 0.0
        self.m_id[arr] = None
        self.m_roles[arr] = None
        if self._band_edges is not None:
            # Slots return to their HOME band (slot ranges are static), so
            # band occupancy self-heals as spilled players match out.
            bands = np.searchsorted(self._band_start, arr, side="right") - 1
            for b in np.unique(bands):
                sel = arr[bands == b][::-1]
                h = self._band_head[b]
                self._band_free[b][h:h + sel.size] = sel
                self._band_head[b] += sel.size
            self._head += arr.size
        else:
            self._free[self._head:self._head + arr.size] = arr
            self._head += arr.size

    def _take_banded(self, ratings: np.ndarray, n: int) -> np.ndarray:
        """Pop ``n`` slots by rating band; spill outward when a band is full.

        Vectorized per band present in the window (≤ R tiny numpy slices);
        the per-request Python loop runs only for spilled requests, which is
        rare until the pool nears capacity or the rating distribution drifts
        from the band edges."""
        band = np.digitize(ratings, self._band_edges)
        slots = np.empty(n, np.int32)
        for b in np.unique(band):
            idx = np.nonzero(band == b)[0]
            h = int(self._band_head[b])
            take = min(idx.size, h)
            if take:
                slots[idx[:take]] = self._band_free[b][h - take:h][::-1]
                self._band_head[b] = h - take
            for j in idx[take:]:
                bb = self._nearest_free_band(int(b))
                hh = int(self._band_head[bb])
                slots[j] = self._band_free[bb][hh - 1]
                self._band_head[bb] = hh - 1
        return slots

    def _nearest_free_band(self, b: int) -> int:
        r = len(self._band_free)
        for off in range(1, r):
            for cand in (b - off, b + off):
                if 0 <= cand < r and self._band_head[cand] > 0:
                    return cand
        raise PoolFullError("no free slot in any band")  # pragma: no cover
        # (unreachable: allocate_columns checks total free space upfront)

    # ---- array building ---------------------------------------------------

    def effective_base_threshold(self, req: SearchRequest) -> float:
        return req.rating_threshold if req.rating_threshold is not None else self.default_threshold

    def batch_arrays_cols(self, cols: RequestColumns, slots: np.ndarray,
                          bucket: int, t_offset: float = 0.0) -> BatchArrays:
        """Pack a columnar window into padded arrays of size ``bucket``.
        Padding lanes get slot = capacity (the sentinel the kernels treat as
        never-matching).

        ``t_offset`` rebases wall-clock timestamps: device times are float32,
        whose spacing at epoch magnitude (~1.7e9 s) is 128 s — far too coarse
        for threshold widening. The engine subtracts its start time so device
        times stay small (sub-millisecond spacing for a week-long process).
        """
        b = len(cols)
        assert b <= bucket
        arr = BatchArrays(
            slot=np.full(bucket, self.capacity, np.int32),
            rating=np.zeros(bucket, np.float32),
            rd=np.zeros(bucket, np.float32),
            region=np.zeros(bucket, np.int32),
            mode=np.zeros(bucket, np.int32),
            threshold=np.zeros(bucket, np.float32),
            enqueue_t=np.zeros(bucket, np.float32),
            valid=np.zeros(bucket, np.bool_),
        )
        if b:
            arr.slot[:b] = slots
            arr.rating[:b] = cols.rating
            arr.rd[:b] = cols.rd
            arr.region[:b] = cols.region
            arr.mode[:b] = cols.mode
            thr = np.where(np.isnan(cols.threshold), self.default_threshold,
                           cols.threshold)
            arr.threshold[:b] = thr
            # Rebase in float64 BEFORE the float32 store: epoch-magnitude
            # seconds only carry 128 s resolution in float32.
            arr.enqueue_t[:b] = cols.enqueued_at - t_offset
            arr.valid[:b] = True
        return arr

    def batch_arrays(self, requests: Sequence[SearchRequest], slots: Sequence[int],
                     bucket: int, t_offset: float = 0.0) -> BatchArrays:
        """Object-path compatibility wrapper around batch_arrays_cols."""
        cols = RequestColumns.from_requests(
            requests, self.regions.code, self.modes.code)
        return self.batch_arrays_cols(cols, np.asarray(slots, np.int32),
                                      bucket, t_offset)

    @staticmethod
    def empty_device_arrays(capacity: int) -> dict[str, np.ndarray]:
        """Initial HBM pool state (all slots inactive)."""
        return {name: np.zeros(capacity, dtype) for name, dtype in POOL_FIELDS}


def band_edges_from_spec(spec: str, n_bands: int) -> list[float] | None:
    """Parse an EngineConfig ``band_spec`` into ``n_bands - 1`` rating edges.

    Formats (JSON/env-friendly single string):
      ``""``                     — banding off (returns None)
      ``"uniform:LO:HI"``        — equal-width bands over [LO, HI]
      ``"gaussian:MEAN:STD"``    — equal-probability-mass bands under
                                   N(MEAN, STD) (stdlib NormalDist quantiles;
                                   matches a typical rating distribution so
                                   bands fill evenly and spilling stays rare)
    """
    if not spec:
        return None
    if n_bands < 2:
        return None
    kind, *params = spec.split(":")
    if kind == "uniform":
        lo, hi = float(params[0]), float(params[1])
        if not hi > lo:
            raise ValueError(f"uniform band_spec needs hi > lo: {spec!r}")
        step = (hi - lo) / n_bands
        return [lo + i * step for i in range(1, n_bands)]
    if kind == "gaussian":
        from statistics import NormalDist

        nd = NormalDist(float(params[0]), float(params[1]))
        return [nd.inv_cdf(i / n_bands) for i in range(1, n_bands)]
    raise ValueError(f"unknown band_spec kind: {spec!r}")


#: Row order of the packed batch (one f32[9, B] array per window — a single
#: host→device transfer; the per-array RPC through the device tunnel is the
#: dominant dispatch cost otherwise). All rows are exact in f32: slot ids and
#: interner codes ≪ 2^24, valid is 0/1. Row 8 carries the rebased ``now``
#: scalar (broadcast across the row; kernels read [8, 0]).
PACKED_ROWS = ("slot", "rating", "rd", "region", "mode", "threshold",
               "enqueue_t", "valid")


def pack_batch(batch: BatchArrays, now: float = 0.0) -> np.ndarray:
    """BatchArrays (+ rebased now) → one f32[9, B] array (unpacked in-kernel)."""
    out = np.empty((len(PACKED_ROWS) + 1, batch.slot.shape[0]), np.float32)
    for i, name in enumerate(PACKED_ROWS):
        out[i] = getattr(batch, name)
    out[8] = now
    return out
