"""Hierarchical rating-bucketed formation (ISSUE 14).

The bucketed step must be BIT-EXACT vs the flat/dense step on identical
pool state — the flat path is the oracle: the device bucket index only
changes WHICH blocks are scored (a superset-bounds argument on top of the
pruned step's span proof), never a single output bit. Same layering as
test_prune.py: randomized equivalence at the kernel seam (traffic +
rescan), the sharded per-bucket frontier vs the single-device dense
kernels at D=2/4, the tournament-tree frontier merge vs the linear merge,
then engine-level integration (adaptive frontier-K, formation_report,
the formation_bucketed mark).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from matchmaking_tpu.config import Config, EngineConfig, QueueConfig
from matchmaking_tpu.core.pool import PACKED_ROWS, PlayerPool
from matchmaking_tpu.engine.kernels import INDEX_FIELDS, KernelSet
from matchmaking_tpu.engine.tpu import TpuEngine
from matchmaking_tpu.service.contract import SearchRequest

pytestmark = pytest.mark.bucketed


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


P, B = 4096, 256
COMMON = dict(capacity=P, top_k=8, pool_block=256,
              widen_per_sec=1.0, max_threshold=200.0)


def _random_pool(rng, sorted_ratings: bool, active_frac=0.7):
    ratings = rng.normal(1500, 300, P).astype(np.float32)
    if sorted_ratings:                       # banded-allocator layout
        ratings = np.sort(ratings)
    return {
        "rating": ratings,
        "rd": rng.uniform(0, 200, P).astype(np.float32),
        "region": rng.integers(0, 3, P).astype(np.int32),
        "mode": rng.integers(0, 3, P).astype(np.int32),
        "threshold": rng.uniform(50, 150, P).astype(np.float32),
        "enqueue_t": rng.uniform(0, 10, P).astype(np.float32),
        "active": rng.random(P) < active_frac,
    }


def _empty_batch():
    return {
        "slot": np.full(B, P, np.int32),
        "rating": np.zeros(B, np.float32),
        "rd": np.zeros(B, np.float32),
        "region": np.zeros(B, np.int32),
        "mode": np.zeros(B, np.int32),
        "threshold": np.zeros(B, np.float32),
        "enqueue_t": np.zeros(B, np.float32),
        "valid": np.zeros(B, bool),
    }


def _random_batch(rng, pool, n_valid=200, banded=False):
    """Window into free slots; ``banded`` draws each lane's rating near its
    slot's block value (what the banded allocator produces in production —
    the layout under which spans stay narrow)."""
    batch = _empty_batch()
    free = np.where(~pool["active"])[0]
    if n_valid and free.size > n_valid:
        free = free[rng.choice(free.size, n_valid, replace=False)]
    free = np.sort(free).astype(np.int32)
    n = free.size
    batch["slot"][:n] = free
    if banded:
        batch["rating"][:n] = (pool["rating"][free]
                               + rng.normal(0, 5, n).astype(np.float32))
    else:
        batch["rating"][:n] = rng.normal(1500, 300, n).astype(np.float32)
    batch["rd"][:n] = rng.uniform(0, 200, n)
    batch["region"][:n] = rng.integers(0, 3, n)
    batch["mode"][:n] = rng.integers(0, 3, n)
    batch["threshold"][:n] = rng.uniform(50, 120, n)
    batch["enqueue_t"][:n] = rng.uniform(0, 10, n)
    batch["valid"][:n] = True
    return batch


def _with_index(ks: KernelSet, pool) -> dict:
    """Pool dict + an EXACT device bucket index (what the engine maintains
    incrementally; rebuilt here so each trial starts tight)."""
    jp = {k: jnp.asarray(v) for k, v in pool.items()}
    jp.update({k: jnp.asarray(v) for k, v in ks.init_index_arrays().items()})
    return ks.index_rebuild(jp)


def _rebuild_copy(ks: KernelSet, pool) -> dict:
    """index_rebuild on COPIES — the jitted rebuild donates its input, so
    comparing against the original requires fresh buffers."""
    return ks.index_rebuild({k: jnp.array(v) for k, v in pool.items()})


def _pack(batch, now: float) -> np.ndarray:
    packed = np.empty((9, B), np.float32)
    for i, name in enumerate(PACKED_ROWS):
        packed[i] = batch[name]
    packed[8] = now
    return packed


def _assert_same(dense_out, buck_pool, buck_out):
    (pd, qd, cd, dd) = dense_out
    np.testing.assert_array_equal(qd, buck_out[0].astype(np.int32))
    np.testing.assert_array_equal(cd, buck_out[1].astype(np.int32))
    hit = qd < P
    # 1-ulp tolerance on distances only: the two programs compile the
    # shared scoring math at different tile shapes (see test_prune).
    np.testing.assert_allclose(dd[hit], buck_out[2][hit], rtol=3e-7,
                               atol=0.0)
    for f in pd:
        np.testing.assert_array_equal(pd[f], np.asarray(buck_pool[f]),
                                      err_msg=f)


def _run_dense(ks, pool, batch, now):
    jb = {k: jnp.asarray(v) for k, v in batch.items()}
    jp = {k: jnp.asarray(v) for k, v in pool.items()}
    p, q, c, d = ks.search_step(jp, jb, jnp.float32(now))
    return ({f: np.asarray(v) for f, v in p.items()},
            np.asarray(q), np.asarray(c), np.asarray(d))


@pytest.mark.parametrize("glicko2", [False, True])
@pytest.mark.parametrize("widen", [0.0, 5.0])
def test_bucketed_step_bit_exact(rng, glicko2, widen):
    """Randomized banded-layout pools: identical outputs, and the
    incrementally-updated index counts equal a fresh exact rebuild's."""
    kw = dict(COMMON, widen_per_sec=widen)
    dense = KernelSet(glicko2=glicko2, **kw)
    buck = KernelSet(glicko2=glicko2, bucketed=True, prune_window_blocks=8,
                     prune_chunk=64, **kw)
    for trial in range(3):
        pool = _random_pool(rng, sorted_ratings=True)
        batch = _random_batch(rng, pool, banded=bool(trial % 2))
        now = 10.0 + trial
        d_out = _run_dense(dense, pool, batch, now)
        bp, out = buck.search_step_packed(_with_index(buck, pool),
                                          jnp.asarray(_pack(batch, now)))
        out = np.asarray(out)
        assert out.shape == (4, B)
        _assert_same(d_out, bp, out)
        assert (d_out[1] < P).sum() > 20   # the trial actually matched
        reb = _rebuild_copy(buck, bp)
        np.testing.assert_array_equal(np.asarray(bp["bidx_count"]),
                                      np.asarray(reb["bidx_count"]))


def test_bucketed_unbanded_pool_falls_back_dense(rng):
    """Random slot layout: every block spans the whole rating range, the
    dense-fallback cond fires (touched == capacity) — still bit-exact."""
    dense = KernelSet(glicko2=False, **COMMON)
    buck = KernelSet(glicko2=False, bucketed=True, prune_window_blocks=2,
                     prune_chunk=64, **COMMON)
    pool = _random_pool(rng, sorted_ratings=False)
    batch = _random_batch(rng, pool)
    d_out = _run_dense(dense, pool, batch, 12.0)
    bp, out = buck.search_step_packed(_with_index(buck, pool),
                                      jnp.asarray(_pack(batch, 12.0)))
    out = np.asarray(out)
    _assert_same(d_out, bp, out)
    assert out[3, 0] == P


def test_bucketed_hot_bucket_touches_fraction(rng):
    """Occupancy-skewed pool (one hot bucket): formation touches a narrow
    span around the hot band, far below the pool — and stays exact."""
    dense = KernelSet(glicko2=False, **COMMON)
    buck = KernelSet(glicko2=False, bucketed=True, prune_window_blocks=6,
                     prune_chunk=64, **COMMON)
    pool = _random_pool(rng, sorted_ratings=True, active_frac=0.0)
    hot = slice(4 * 256, 6 * 256)           # blocks 4-5 only
    pool["active"][hot] = rng.random(512) < 0.9
    batch = _empty_batch()
    free = np.where(~pool["active"][hot])[0][:40] + hot.start
    free = free.astype(np.int32)
    n = free.size
    batch["slot"][:n] = free
    batch["rating"][:n] = (pool["rating"][free]
                           + rng.normal(0, 3, n).astype(np.float32))
    batch["rd"][:n] = rng.uniform(0, 100, n)
    batch["threshold"][:n] = rng.uniform(50, 100, n)
    batch["valid"][:n] = True
    d_out = _run_dense(dense, pool, batch, 5.0)
    bp, out = buck.search_step_packed(_with_index(buck, pool),
                                      jnp.asarray(_pack(batch, 5.0)))
    out = np.asarray(out)
    _assert_same(d_out, bp, out)
    assert (d_out[1] < P).sum() > 5
    assert out[3, 0] < P / 2                # sub-O(P): narrow hot span


def test_bucketed_widening_expands_candidate_buckets(rng):
    """Threshold widening grows the candidate BUCKET SET: the admissible
    span width (the number of buckets a chunk may reach) strictly grows
    as the same waiting players age — and the cut stays bit-exact vs
    dense at every age, including past the span budget (dense fallback)."""
    from matchmaking_tpu.engine.kernels import _effective_threshold

    dense = KernelSet(glicko2=False, **COMMON)
    buck = KernelSet(glicko2=False, bucketed=True, prune_window_blocks=12,
                     prune_chunk=32, **COMMON)
    pool = _random_pool(rng, sorted_ratings=True, active_frac=0.0)
    mid = slice(6 * 256, 10 * 256)
    pool["active"][mid] = rng.random(4 * 256) < 0.5
    pool["threshold"][:] = 20.0
    pool["enqueue_t"][:] = 0.0
    batch = _empty_batch()
    free = np.where(~pool["active"][mid])[0][:60] + mid.start
    free = free.astype(np.int32)
    n = free.size
    batch["slot"][:n] = free
    batch["rating"][:n] = (pool["rating"][free]
                           + rng.normal(0, 2, n).astype(np.float32))
    batch["threshold"][:n] = 20.0
    batch["enqueue_t"][:n] = 0.0
    batch["valid"][:n] = True

    def span_widths(now: float) -> np.ndarray:
        """The kernel's own admissible-bucket widths for busy chunks."""
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        qte = _effective_threshold(jb["threshold"], jb["enqueue_t"],
                                   jnp.float32(now), buck.widen_per_sec,
                                   buck.max_threshold)
        sb, qte_s, _ = buck._sort_batch(jb, qte)
        jp = {k: jnp.asarray(v) for k, v in pool.items()}
        lmin, lmax, lrd = buck._live_stats(jp)
        imin, imax, ird = buck._incoming_stats(sb)
        _, _, width = buck._chunk_windows(
            sb, qte_s, jnp.minimum(lmin, imin), jnp.maximum(lmax, imax),
            jnp.maximum(lrd, ird))
        w = np.asarray(width)
        return w[w > 0]

    early, late = span_widths(1.0), span_widths(120.0)
    assert late.max() > early.max()         # aged players reach further
    touched = []
    for now in (1.0, 120.0):                # widen 1/s, cap 200
        d_out = _run_dense(dense, pool, batch, now)
        bp, out = buck.search_step_packed(_with_index(buck, pool),
                                          jnp.asarray(_pack(batch, now)))
        out = np.asarray(out)
        _assert_same(d_out, bp, out)
        touched.append(float(out[3, 0]))
    assert touched[0] < P                   # young cut stayed sub-pool


def test_bucketed_rescan_bit_exact(rng):
    """The no-admission bucketed rescan vs the flat rescan variant:
    identical matches + pool state, index counts stay exact."""
    flat = KernelSet(glicko2=False, **COMMON)
    buck = KernelSet(glicko2=False, bucketed=True, prune_window_blocks=8,
                     prune_chunk=64, **COMMON)
    pool = _random_pool(rng, sorted_ratings=True)
    batch = _empty_batch()
    act = np.where(pool["active"])[0][:220].astype(np.int32)
    n = act.size
    batch["slot"][:n] = act
    batch["rating"][:n] = pool["rating"][act]
    batch["rd"][:n] = pool["rd"][act]
    batch["region"][:n] = pool["region"][act]
    batch["mode"][:n] = pool["mode"][act]
    batch["threshold"][:n] = pool["threshold"][act]
    batch["enqueue_t"][:n] = pool["enqueue_t"][act]
    # A few stale lanes (already-evicted slots) ride along masked.
    stale = np.where(~pool["active"])[0][:8].astype(np.int32)
    batch["slot"][n:n + 8] = stale
    batch["valid"][:n + 8] = True
    packed = _pack(batch, 14.0)
    jf = {k: jnp.asarray(v) for k, v in pool.items()}
    pf, outf = flat.search_step_packed_rescan(jf, jnp.asarray(packed))
    pb, outb = buck.search_step_packed_rescan(_with_index(buck, pool),
                                              jnp.asarray(packed))
    outf, outb = np.asarray(outf), np.asarray(outb)
    np.testing.assert_array_equal(outf[0], outb[0])
    np.testing.assert_array_equal(outf[1], outb[1])
    for f in pf:
        np.testing.assert_array_equal(np.asarray(pf[f]),
                                      np.asarray(pb[f]), err_msg=f)
    assert (outf[0].astype(np.int32) < P).sum() > 10
    reb = _rebuild_copy(buck, pb)
    np.testing.assert_array_equal(np.asarray(pb["bidx_count"]),
                                  np.asarray(reb["bidx_count"]))


def test_indexed_admit_evict_keep_counts_exact(rng):
    """The standalone indexed admit (restore path) and evict (remove/
    expire path) keep the device counts equal to an exact rebuild, and
    double-eviction counts nothing (idempotence)."""
    buck = KernelSet(glicko2=False, bucketed=True, prune_window_blocks=8,
                     prune_chunk=64, **COMMON)
    pool = _random_pool(rng, sorted_ratings=True, active_frac=0.3)
    jp = _with_index(buck, pool)
    batch = _random_batch(rng, pool, n_valid=100)
    jp = buck.admit_packed(jp, jnp.asarray(_pack(batch, 0.0)))
    jp = {k: np.asarray(v) for k, v in jp.items()}
    reb = _rebuild_copy(buck, jp)
    np.testing.assert_array_equal(jp["bidx_count"],
                                  np.asarray(reb["bidx_count"]))
    # Bounds stay a superset of the exact rebuild's.
    assert (jp["bidx_min"] <= np.asarray(reb["bidx_min"]) + 1e-6).all()
    assert (jp["bidx_max"] >= np.asarray(reb["bidx_max"]) - 1e-6).all()
    ev = np.full(buck.evict_bucket, P, np.int32)
    victims = np.where(jp["active"])[0][:16].astype(np.int32)
    ev[:victims.size] = victims
    jp = buck.evict({k: jnp.asarray(v) for k, v in jp.items()},
                    jnp.asarray(ev))
    jp = {k: np.asarray(v) for k, v in jp.items()}
    reb = _rebuild_copy(buck, jp)
    np.testing.assert_array_equal(jp["bidx_count"],
                                  np.asarray(reb["bidx_count"]))
    jp2 = buck.evict({k: jnp.asarray(v) for k, v in jp.items()},
                     jnp.asarray(ev))      # double evict: no-op counts
    np.testing.assert_array_equal(np.asarray(jp2["bidx_count"]),
                                  jp["bidx_count"])


# ---- sharded per-bucket frontier -------------------------------------------


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_bucket_frontier_equals_dense(rng, n_shards):
    """D=2/4 bucket-frontier step vs the single-device dense kernels on
    identical (sparse) pool state: identical matches and pool state —
    only per-bucket top-K frontiers crossed the shard boundary."""
    from matchmaking_tpu.engine.sharded import ShardedKernelSet, pool_mesh

    sh = ShardedKernelSet(capacity=P, top_k=8, pool_block=256,
                          glicko2=False, widen_per_sec=1.0,
                          max_threshold=200.0, mesh=pool_mesh(n_shards),
                          bucket_frontier_k=64)
    dense = KernelSet(glicko2=False, **COMMON)
    pool = _random_pool(rng, sorted_ratings=True, active_frac=0.012)
    batch = _random_batch(rng, pool, n_valid=100, banded=True)
    d_out = _run_dense(dense, pool, batch, 10.0)
    sp = sh.place_pool(dict(pool))
    p2, out = sh.bucket_step(64)(sp, jnp.asarray(_pack(batch, 10.0)))
    out = np.asarray(out)
    _assert_same(d_out, p2, out)
    assert (d_out[1] < P).sum() > 10
    assert out[3, 0] < P                    # occupancy-shaped formation


# ---- tournament-tree frontier merge ----------------------------------------


def test_tournament_merge_helper_matches_concat_sort(rng):
    """Unit: tree top-k merge of sorted frontiers == numpy concat +
    stable lexsort + truncate, including cross-shard ties."""
    from matchmaking_tpu.engine.sharded import tournament_merge_topk

    k, shards = 16, 4
    bufs, keys = [], []
    for s in range(shards):
        group = np.sort(rng.integers(0, 4, k)).astype(np.int32)
        rating = np.sort(rng.integers(0, 6, k)).astype(np.float32)
        order = np.lexsort((rating, group))
        gslot = (s * 100 + np.arange(k)).astype(np.int32)
        buf = np.stack([group[order].astype(np.float32),
                        rating[order], gslot.astype(np.float32)])
        bufs.append(jnp.asarray(buf))
        keys.append((buf[0].astype(np.int32), buf[1],
                     buf[2].astype(np.int32)))

    def key_fn(fb):
        return (fb[0].astype(jnp.int32), fb[1], fb[2].astype(jnp.int32))

    merged = np.asarray(tournament_merge_topk(bufs, key_fn))
    cat = np.concatenate([np.asarray(b) for b in bufs], axis=1)
    order = np.lexsort((cat[2], cat[1], cat[0]))[:k]
    np.testing.assert_array_equal(merged, cat[:, order])


@pytest.mark.parametrize("n_shards", [2, 4])
def test_role_ring_tournament_equals_linear(rng, n_shards):
    """The ROLE ring step with the tournament consumer merge (role_mask
    rides the frontier rows through the merge; the K-row _ring_form
    drives _windows_roles/_cover_split) is bit-identical to linear."""
    from matchmaking_tpu.engine.role_kernels import (
        ShardedRoleKernelSet,
        RoleKernelSet,
    )
    from matchmaking_tpu.engine.sharded import pool_mesh

    cap, bb, k = 512, 64, 32
    mk = dict(capacity=cap, team_size=2, role_slots=("tank", "dps"),
              widen_per_sec=0.5, max_threshold=200.0, max_matches=8,
              rounds=8, frontier_k=k)
    lin = ShardedRoleKernelSet(mesh=pool_mesh(n_shards), **mk)
    tour = ShardedRoleKernelSet(mesh=pool_mesh(n_shards),
                                frontier_merge="tournament", **mk)
    pool = {
        "rating": rng.normal(1500, 40, cap).astype(np.float32),
        "rd": rng.uniform(0, 200, cap).astype(np.float32),
        "region": np.ones(cap, np.int32),
        "mode": np.ones(cap, np.int32),
        "threshold": rng.uniform(100, 180, cap).astype(np.float32),
        "enqueue_t": rng.uniform(0, 5, cap).astype(np.float32),
        "active": np.zeros(cap, bool),
        "role_mask": np.zeros(cap, np.int32),
    }
    act = rng.choice(cap, k - 6, replace=False)
    pool["active"][act] = True
    pool["role_mask"][act] = rng.integers(1, 4, act.size)  # tank/dps/both
    batch = {f: np.zeros(bb, dt) for f, dt in
             [("slot", np.int32), ("rating", np.float32),
              ("rd", np.float32), ("region", np.int32),
              ("mode", np.int32), ("threshold", np.float32),
              ("enqueue_t", np.float32), ("valid", bool)]}
    batch["slot"][:] = cap
    free = np.where(~pool["active"])[0][:4].astype(np.int32)
    batch["slot"][:4] = free
    batch["rating"][:4] = rng.normal(1500, 40, 4).astype(np.float32)
    batch["region"][:4] = 1
    batch["mode"][:4] = 1
    batch["threshold"][:4] = 150.0
    batch["valid"][:4] = True
    packed = np.empty((9, bb), np.float32)
    for i, name in enumerate(PACKED_ROWS):
        packed[i] = batch[name]
    packed[8] = 8.0
    # Insert the role_mask row before the trailing now row (pack_rows).
    masks = np.zeros((1, bb), np.float32)
    masks[0, :4] = rng.integers(1, 4, 4)
    rpacked = np.concatenate([packed[:8], masks, packed[8:]])
    pl = lin.place_pool(dict(pool))
    pt = tour.place_pool(dict(pool))
    p1, o1 = lin.search_step_packed_ring(pl, jnp.asarray(rpacked))
    p2, o2 = tour.search_step_packed_ring(pt, jnp.asarray(rpacked))
    o1, o2 = np.asarray(o1), np.asarray(o2)
    np.testing.assert_array_equal(o1, o2)
    for f in p1:
        np.testing.assert_array_equal(np.asarray(p1[f]),
                                      np.asarray(p2[f]), err_msg=f)
    assert (o1[0] < cap).sum() >= 1         # a role match actually formed


@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_team_ring_tournament_equals_linear(rng, n_shards):
    """The team ring step with the tournament consumer merge is
    bit-identical to the linear merge under the shared host gate."""
    from matchmaking_tpu.engine.sharded import pool_mesh
    from matchmaking_tpu.engine.teams import ShardedTeamKernelSet

    cap, bb, k = 1024, 64, 32
    mk = dict(capacity=cap, team_size=2, widen_per_sec=0.5,
              max_threshold=200.0, max_matches=16, rounds=8, frontier_k=k)
    lin = ShardedTeamKernelSet(mesh=pool_mesh(n_shards), **mk)
    tour = ShardedTeamKernelSet(mesh=pool_mesh(n_shards),
                                frontier_merge="tournament", **mk)
    pool = {
        "rating": rng.normal(1500, 40, cap).astype(np.float32),
        "rd": rng.uniform(0, 200, cap).astype(np.float32),
        "region": np.ones(cap, np.int32),
        "mode": np.ones(cap, np.int32),
        "threshold": rng.uniform(100, 180, cap).astype(np.float32),
        "enqueue_t": rng.uniform(0, 5, cap).astype(np.float32),
        "active": np.zeros(cap, bool),
    }
    pool["active"][rng.choice(cap, k - 6, replace=False)] = True
    batch = {f: np.zeros(bb, dt) for f, dt in
             [("slot", np.int32), ("rating", np.float32),
              ("rd", np.float32), ("region", np.int32),
              ("mode", np.int32), ("threshold", np.float32),
              ("enqueue_t", np.float32), ("valid", bool)]}
    batch["slot"][:] = cap
    free = np.where(~pool["active"])[0][:4].astype(np.int32)
    batch["slot"][:4] = free
    batch["rating"][:4] = rng.normal(1500, 40, 4).astype(np.float32)
    batch["region"][:4] = 1
    batch["mode"][:4] = 1
    batch["threshold"][:4] = 150.0
    batch["valid"][:4] = True
    packed = np.empty((9, bb), np.float32)
    for i, name in enumerate(PACKED_ROWS):
        packed[i] = batch[name]
    packed[8] = 8.0
    pl = lin.place_pool(dict(pool))
    pt = tour.place_pool(dict(pool))
    p1, o1 = lin.search_step_packed_ring(pl, jnp.asarray(packed))
    p2, o2 = tour.search_step_packed_ring(pt, jnp.asarray(packed))
    o1, o2 = np.asarray(o1), np.asarray(o2)
    np.testing.assert_array_equal(o1, o2)
    for f in p1:
        np.testing.assert_array_equal(np.asarray(p1[f]),
                                      np.asarray(p2[f]), err_msg=f)
    assert (o1[0] < cap).sum() >= 1         # formation actually formed


# ---- engine integration ----------------------------------------------------


def _engine(**kw) -> TpuEngine:
    ec = EngineConfig(backend="tpu", pool_capacity=4096, pool_block=256,
                      batch_buckets=(16, 64, 256),
                      band_spec="gaussian:1500:300", **kw)
    cfg = Config(engine=ec,
                 queues=(QueueConfig(rating_threshold=100.0,
                                     widen_per_sec=2.0,
                                     max_threshold=200.0),))
    return TpuEngine(cfg, cfg.queues[0])


def _feed(engine: TpuEngine):
    """Identical request stream incl. an expiry sweep + heartbeat (the
    index-rebuild tick) mid-stream; returns the sorted match set."""
    out = []
    local = np.random.default_rng(7)
    for w in range(6):
        reqs = [SearchRequest(id=f"w{w}_{i}",
                              rating=float(local.normal(1500, 300)),
                              enqueued_at=1000.0 + w)
                for i in range(120)]
        res = engine.search(reqs, now=1000.0 + w)
        out.extend((tuple(sorted(m.result().players)),
                    round(m.quality, 5)) for m in res.matches)
        if w == 3:
            engine.expire(1000.0 + w, 0.5)
            engine.heartbeat(1000.0 + w)
    return sorted(out)


def test_engine_bucketed_matches_flat():
    """Same stream + same banded allocator, bucketed vs flat kernels:
    identical match sets end-to-end through the engine (expiry + the
    heartbeat index rebuild included)."""
    flat = _feed(_engine())
    buck = _feed(_engine(bucketed=True, prune_window_blocks=8))
    assert len(flat) > 100
    assert flat == buck


def test_engine_sharded_bucket_frontier_matches_flat():
    """D=2 bucket-frontier engine == flat single-device engine, with the
    adaptive-K ladder choosing from observed occupancy and recording its
    moves."""
    flat = _feed(_engine())
    e = _engine(bucketed=True, mesh_pool_axis=2, bucket_frontier_k=64)
    sharded = _feed(e)
    assert flat == sharded
    rep = e.formation_report()
    assert rep["mode"] == "bucket_frontier"
    assert rep["frontier_k"] in rep["frontier_ladder"]
    assert rep["frontier_steps"] > 0
    assert len(e.frontier_moves) >= 1       # audit ring saw the sizing
    assert rep["formation_touched_frac"] < 0.35
    assert rep["bands"] is not None         # free-slot heads surfaced


def test_engine_frontier_fallback_above_ladder():
    """Occupancy above the ladder ceiling must fall back to the dense
    sharded step (counted) — and stay correct."""
    flat = _feed(_engine())
    e = _engine(bucketed=True, mesh_pool_axis=2, bucket_frontier_k=8)
    sharded = _feed(e)
    assert flat == sharded
    assert e.counters.get("bucket_frontier_fallback", 0) > 0


def test_formation_report_and_marks():
    """Flat engines report no formation state; bucketed engines report it
    and stamp formation_bucketed device marks for the attribution
    taxonomy."""
    plain = _engine()
    assert plain.formation_report() is None
    e = _engine(bucketed=True, prune_window_blocks=8)
    reqs = [SearchRequest(id=f"p{i}", rating=1500.0 + i, enqueued_at=1.0)
            for i in range(40)]
    e.search_async(reqs, now=1.0)
    done = e.flush()
    assert done
    marks = [name for name, _ in e.window_marks[done[0][0]]]
    assert "formation_bucketed" in marks
    assert "device_step" not in marks
    rep = e.formation_report()
    assert rep["mode"] == "bucketed"
    assert rep["windows"] >= 1
    assert rep["touched_slots"] > 0
    from matchmaking_tpu.service.attribution import classify

    cat, kind = classify("h2d", "formation_bucketed")
    assert cat == "formation_bucketed" and kind == "work"
