"""Typed configuration for the whole framework.

Replaces the reference's Mix config (``config/*.exs`` + env vars: broker URL,
queue names, pool size, default ``rating_threshold`` — SURVEY.md §2 C10, §5
"Config/flag system"). One frozen dataclass tree, loadable from JSON or
environment variables, passed explicitly (no global mutable config).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence


@dataclass(frozen=True)
class QueueConfig:
    """Per-matchmaking-queue knobs (the reference partitions work across AMQP
    queues per game-mode/region — SURVEY.md §2 "Queue sharding")."""

    #: AMQP queue this engine consumes (must equal what clients publish to —
    #: BrokerConfig.request_queue points at the default one).
    name: str = "matchmaking.search"
    #: Game mode this queue serves. ``None`` → mode taken from each request.
    game_mode: str | None = None
    #: Players per team. 1 → 1v1; 5 → 5v5 team-balanced (BASELINE config #3).
    team_size: int = 1
    #: Default max |rating_a - rating_b| for a valid match (reference knob
    #: ``rating_threshold`` — BASELINE.json north_star).
    rating_threshold: float = 100.0
    #: Threshold widening: effective threshold grows by this many rating
    #: points per second waited (0 disables; SURVEY.md §2 C9 notes widening is
    #: typical but unverified in the reference, so it is config-gated).
    widen_per_sec: float = 0.0
    #: Cap on the widened threshold.
    max_threshold: float = 400.0
    #: Use Glicko-2 rating-deviation-weighted scoring (BASELINE config #4).
    #: Applies to 1v1 distance; team queues use plain rating spread.
    glicko2: bool = False
    #: Require role coverage for team formation (BASELINE config #5).
    role_slots: tuple[str, ...] = ()
    #: Evict waiting players after this many seconds and answer ``timeout``
    #: (None → wait forever, durability delegated to the broker like the
    #: reference's volatile ETS pool — SURVEY.md §5 checkpoint/resume).
    request_timeout_s: float | None = None
    #: Publish an immediate ``queued`` ack when a request enters the pool
    #: (the matched response follows on the same reply queue when found).
    send_queued_ack: bool = True
    #: At-least-once dedup horizon: a redelivered/duplicated request whose
    #: player reached a terminal state (matched/timeout) within this many
    #: seconds is answered with the cached response instead of re-entering
    #: the pool (prevents one player landing in two matches).
    dedup_ttl_s: float = 30.0
    #: Default QoS priority tier for requests arriving WITHOUT an
    #: ``x-tier`` header on this queue (service/overload.py: tier 0 is the
    #: most latency-critical; higher numbers shed first). Only meaningful
    #: when ``OverloadConfig.tiers > 1`` — a ranked queue defaults to 0, a
    #: bot-fill queue to the lowest configured tier.
    default_tier: int = 0
    #: Periodic rescan of the longest-waiting players (seconds; 0 = off).
    #: Matching is otherwise arrival-triggered (reference semantics), so two
    #: waiting players whose thresholds WIDENED into compatibility would
    #: never match under zero traffic; the rescan re-submits the oldest
    #: waiting window so widening can resolve. Only meaningful with
    #: ``widen_per_sec > 0`` on 1v1 queues.
    rescan_interval_s: float = 0.0
    #: Players covered per rescan tick (0 → the batcher's max_batch).
    #: Device 1v1 queues rescan through a no-admission step that is safe to
    #: overlap in-flight windows AND to split into multiple device chunks
    #: (kernels._rescan_step), so this may exceed one batch bucket. A tick
    #: dispatches at most ``EngineConfig.pipeline_depth`` chunks (largest
    #: bucket each) so a pool-sized window cannot queue tens of device
    #: steps ahead of traffic; the oldest-first pick rolls the remainder
    #: into later ticks — size pipeline_depth × largest bucket ≳ pool to
    #: resolve widening pool-wide in a single tick.
    rescan_window: int = 0


@dataclass(frozen=True)
class EngineConfig:
    """Engine selection + device-pool geometry."""

    #: ``"cpu"`` → NumPy oracle with the reference's sequential-scan
    #: semantics; ``"tpu"`` → batched JAX engine. The seam mirrors the
    #: reference's ``Matchmaking.Engine`` behaviour (SURVEY.md §2 C6).
    backend: str = "cpu"
    #: Fixed device-pool capacity P (static shape; slots are recycled).
    pool_capacity: int = 131_072
    #: Candidates kept per request before conflict resolution.
    top_k: int = 8
    #: Request-window batch buckets (padded to the smallest bucket ≥ batch) —
    #: static shapes keep XLA from recompiling in the hot path (p99 killer,
    #: SURVEY.md §7 "Hard parts").
    batch_buckets: tuple[int, ...] = (16, 64, 256, 1024)
    #: Pool-shard mesh axis size (1 → single device). Multi-chip: pool slots
    #: are sharded over axis ``"pool"`` and merged with XLA collectives.
    mesh_pool_axis: int = 1
    #: Use ring (ppermute) top-k merge instead of all_gather when sharded.
    ring_merge: bool = False
    #: Score tile size over the pool dimension (blockwise scoring keeps the
    #: B×P score matrix out of HBM at P=100k; SURVEY.md §7 "Hard parts").
    pool_block: int = 8192
    #: Proposal rounds in the parallel greedy pairing kernel. Each round
    #: resolves all non-conflicting best edges at once; leftovers (rare —
    #: they need ≥``pair_rounds`` collisions on their top-k list) stay in
    #: the pool for the next window.
    pair_rounds: int = 8
    #: Team queues (device path): max matches extracted per step and
    #: parallel-greedy window-selection rounds (engine/teams.py).
    team_max_matches: int = 1024
    team_rounds: int = 16
    #: Ring-scaled sharded team/role window formation (mesh_pool_axis > 1).
    #: 0 = replicated fallback only: every step all_gathers the full pool
    #: columns — O(P) ICI bytes and O(P) per-device window math regardless
    #: of shard count. N > 0 = per-shard top-N candidate frontier: each
    #: shard compacts its (group, rating)-sorted slice to N rows and the
    #: frontiers travel the ICI ring via ppermute (D−1 neighbor hops) —
    #: O(P/D + N·D) per device, bit-identical to the fallback while pool
    #: occupancy stays <= N (the host checks per window and silently falls
    #: back above it, counted in engine_counters team_ring_fallback). Size
    #: N at the expected concurrent WAITING population, not capacity; see
    #: BENCH_SWEEP.md §8 for the measured crossover.
    team_ring_k: int = 0
    #: Max dispatched-but-uncollected windows the SERVICE keeps in flight on
    #: the pipelined columnar path (1 = the old dispatch-then-block flush).
    #: Pipelining hides the host↔device round trip — measured on the axon
    #: tunnel: a single D2H readback has ~70 ms latency and readbacks
    #: serialize, so depth 2 keeps the transfer channel busy while window
    #: N+1 computes; deeper only queues latency (see BENCH_SWEEP.md).
    pipeline_depth: int = 2
    #: Device-side readback grouping: stack this many result arrays (one
    #: per dispatched window chunk — a window larger than the top batch
    #: bucket contributes one per chunk) ON DEVICE and transfer them to
    #: host as ONE array. What is amortized is TRANSFERS: the host
    #: link is the measured bottleneck (one D2H ≈ 70 ms fixed latency,
    #: transfers serialized ≈ 12-14/s on the axon tunnel), so one transfer
    #: per k windows multiplies result throughput by ~k at the cost of up
    #: to (k-1) device-step times of extra latency for the group's first
    #: window. 1 = off (one transfer per window). Groups seal early when a
    #: caller collects (collect_ready/flush), so idle traffic is not held
    #: back a full group.
    readback_group: int = 1
    #: Age (ms) after which a partially-filled readback group is sealed and
    #: transferred anyway (checked on every collect_ready poll) — bounds
    #: the extra latency grouping can add when traffic pauses mid-group.
    readback_group_wait_ms: float = 8.0
    #: Compile every (batch bucket × step variant) executable at app start
    #: (Engine.warmup) instead of lazily on first use. The engine ships TWO
    #: compiled 1v1 step variants (full and all-ANY-window, see
    #: kernels.KernelSet); without warmup the first window that needs the
    #: OTHER variant stalls on an XLA compile inline on the serving path —
    #: the recompile cliff the bucketing exists to prevent. Off by default
    #: (tests build many small engines; serve/bench turn it on).
    warm_start: bool = False
    #: Rating-banded candidate pruning (single-device 1v1 path). 0 = dense
    #: scoring of every pool block. N > 0: each rating-sorted window chunk
    #: scores only an N-block contiguous span of the pool chosen from live
    #: per-block rating bounds — BIT-EXACT vs dense (a whole-window dense
    #: fallback cond covers spans that don't fit; kernels.py
    #: ``_search_step_pruned``). Effective only with ``band_spec`` set so
    #: the allocator keeps blocks rating-coherent. Size so that
    #: N·(capacity/n_blocks) slots cover ~2·max effective threshold of
    #: rating mass (Glicko-2: /g(max rd)) for the mid-distribution chunks.
    prune_window_blocks: int = 0
    #: Sorted-window chunk size for pruning: smaller chunks → tighter rating
    #: intervals → narrower spans, but more scan iterations per window.
    prune_chunk: int = 128
    #: Rating-band layout for the HOST slot allocator (core/pool.py
    #: ``band_edges_from_spec``): "" (off), "uniform:LO:HI", or
    #: "gaussian:MEAN:STD" (equal-mass bands — keeps band occupancy even
    #: under a normal rating distribution). One band per pool block.
    band_spec: str = ""
    #: Hierarchical rating-bucketed formation (ISSUE 14). Single-device 1v1
    #: queues: the pool dict carries a device-resident bucket index (per-
    #: block occupancy + conservative rating bounds, maintained
    #: incrementally by every admit/match/evict; kernels.INDEX_FIELDS) and
    #: window formation cuts candidate spans from the INDEX instead of
    #: re-deriving block bounds with an O(P) per-window scan — sub-O(P)
    #: formation, bit-exact vs the flat step (dense-fallback cond above
    #: span overflow), with the touched-slot fraction reported per window.
    #: Most effective with ``band_spec`` set (rating-coherent blocks);
    #: without it the step stays correct but mostly falls back to dense.
    #: Sharded 1v1 queues additionally need ``bucket_frontier_k``.
    bucketed: bool = False
    #: Per-bucket top-K frontier exchange for SHARDED 1v1 queues
    #: (mesh_pool_axis > 1; engine/sharded.py ``bucket_step``): each shard
    #: compacts every local pool block into its top-K active rows and only
    #: those frontiers cross the shard boundary (ppermute ring) — ICI
    #: traffic and formation work become occupancy-shaped (O(nb·K))
    #: instead of capacity-shaped (O(P)). This value is the LADDER
    #: CEILING: the engine sizes the actual K per window from the
    #: mirror's observed per-bucket occupancy (powers of two up to here,
    #: compiled lazily per K, moves audited in /debug/placement) and
    #: falls back to the dense sharded step when any bucket overflows —
    #: which is the bit-exactness gate. 0 = off.
    bucket_frontier_k: int = 0
    #: Consumer merge for ring-gathered team/role frontiers
    #: (``teams.merge_frontiers``): "linear" concatenates all D·K rows in
    #: canonical shard order (the PR 1 path); "tournament" merges the D
    #: already-sorted K-row frontiers up a pairwise tree keeping top-K —
    #: the formation buffer shrinks from O(K·D) to O(K) (working set
    #: O(K·log D)), bit-exact under the ring path's existing occupancy
    #: gate.
    frontier_merge: str = "linear"
    #: Device-engine circuit breaker (service/breaker.py): after this many
    #: engine crashes within ``breaker_window_s`` the queue's breaker trips
    #: OPEN and the queue is demoted to the host-oracle engine — matches
    #: keep flowing at oracle throughput instead of revive-looping a
    #: persistently failing device path at full traffic rate. 0 disables
    #: (every crash revives the device engine immediately, the pre-breaker
    #: behavior). Device (``backend="tpu"``) queues only.
    breaker_threshold: int = 0
    #: Sliding crash-count window for the trip decision (seconds).
    breaker_window_s: float = 30.0
    #: Half-open probe schedule while the breaker is open: the first probe
    #: runs ``breaker_probe_initial_s`` after the trip; each FAILED probe
    #: multiplies the delay by ``breaker_probe_backoff`` up to
    #: ``breaker_probe_max_s`` (exponential backoff — a dead device is not
    #: hammered). A probe builds a fresh device engine and runs one no-op
    #: step end to end; success re-promotes the queue (pool transferred
    #: back, breaker CLOSED).
    breaker_probe_initial_s: float = 1.0
    breaker_probe_backoff: float = 2.0
    breaker_probe_max_s: float = 60.0
    #: Dedicated low-frequency health timer (seconds; 0 disables). Drives
    #: the half-open breaker probes AND the idle re-promotion heartbeat for
    #: wildcard-delegated team/role queues — independent of ``_rescan_loop``,
    #: so a delegated queue with ``rescan_interval_s=0`` still re-promotes
    #: once its wildcards drain (ADVICE round-5 #3).
    health_interval_s: float = 1.0
    #: Speculative formation (ISSUE 16): spend idle window-gap device
    #: cycles precomputing pool-resident pairings (an ahead-of-time rescan
    #: tick over the resident pool), then commit the precomputed window in
    #: O(delta) at the next cut — or discard and fall back bit-exactly to
    #: the full step when any pool mutation invalidated the basis. Off by
    #: default: it trades wasted speculative steps (free on an idle device)
    #: for turnaround latency, which only pays on gappy traffic.
    spec_formation: bool = False
    #: Max chained speculative steps per gap (each runs on the previous
    #: speculative pool; matched-slot re-selection is a device-side no-op).
    spec_max_steps: int = 2
    #: Staleness bound (ms): a speculation older than this at commit time
    #: is discarded even if no mutation invalidated it — with widening on,
    #: a committed window is "the rescan evaluated at speculation time",
    #: and this caps how far in the past that evaluation may sit.
    spec_staleness_ms: float = 500.0
    #: Gap-poll cadence for the service speculation loop (ms; 0 disables
    #: the loop even with spec_formation on — cut-path commit still runs).
    spec_interval_ms: float = 10.0


@dataclass(frozen=True)
class BrokerConfig:
    """In-process AMQP-semantic broker knobs (SURVEY.md §2 "Distributed
    communication backend": real RabbitMQ is not available in this
    environment, so an in-process broker implements identical semantics
    behind the same interface)."""

    url: str = "inproc://matchmaking"
    request_queue: str = "matchmaking.search"
    #: Per-consumer unacked-message cap (AMQP basic.qos prefetch).
    prefetch: int = 2048
    #: Redelivery attempts for nacked/dropped deliveries (at-least-once).
    max_redelivery: int = 3
    #: Window-granular egress (ISSUE 9): the service publishes a whole
    #: window's responses through one ``publish_batch`` broker call instead
    #: of one ``publish`` per response — publish_lag collapses from
    #: O(matches) callbacks to O(windows). Per-message semantics (trace
    #: stamping, chaos seq accounting, dup faults) are preserved: items
    #: needing them take the full publish() path inside the batch. False =
    #: the per-response path, byte for byte.
    batch_publish: bool = True
    #: Columnar consume_batch ingress (ISSUE 12 — the decode side of the
    #: batch response encoder): the broker drains whole bursts of buffered
    #: deliveries and hands the app ONE callback per burst instead of one
    #: handler invocation + bookkeeping per delivery; the app then runs
    #: admission pre-checks, the native batch request decode (one C call
    #: over the burst's concatenated bodies + offsets), and the batcher
    #: hand-off burst-granular. Per-delivery semantics are preserved: a
    #: broker with consume-side fault injection armed (chaos drops, delay)
    #: keeps the per-delivery handler path so fault identity replays
    #: bit-identically, and auth-RPC services keep per-delivery tasks (the
    #: round trips must overlap). False = the per-delivery PR 9 path, byte
    #: for byte.
    consume_batch: bool = True
    #: Deliveries per consume burst (the batch callback's max rows; also
    #: the AMQP loop-bridge coalescing cap).
    consume_batch_max: int = 256
    #: In-process ingress shard workers per queue (ISSUE 12): a burst's
    #: contract-fallback rows are consistent-hashed (crc32 of the
    #: correlation id — the request identity available pre-decode) into N
    #: worker slices, and the shard columns merge at the EDF cut feeding
    #: the single device engine. The terminal-replay dedup cache is
    #: independently split into per-shard dicts by player id; shard
    #: workers never touch it (the probe runs at the cut, on the event
    #: loop), and the remaining ingress state (admission credits,
    #: batcher) stays event-loop-confined and is proven
    #: settle-exactly-once by matchlint's settlement typestate — which is
    #: what keeps the whole split lock-free. 1 = today's single-worker
    #: path, byte for byte. N > 1 runs shard slices on worker threads
    #: (the native decode and numpy assembly release the GIL, so
    #: multi-core hosts parallelize ingress).
    ingress_shards: int = 1
    # Fault-injection hooks (SURVEY.md §5 "Failure detection").
    drop_prob: float = 0.0
    dup_prob: float = 0.0
    delay_ms: float = 0.0


@dataclass(frozen=True)
class ChaosConfig:
    """Deterministic, scriptable fault schedule (SURVEY.md §5 "Failure
    detection") — the replay-exact successor to the probabilistic
    ``BrokerConfig.drop_prob``/``dup_prob`` hooks. Two fault families:

    - **Scripted** faults fire at exact sequence indices: per-queue publish
      sequence numbers for broker faults (``drop_seqs``/``dup_seqs``/
      ``partitions``), per-queue device SEARCH-step indices for engine
      faults (``fail_steps``/``fail_step_ranges`` — admits, evicts and
      restores are exempt so crash recovery itself cannot be failed).
    - **Seeded** faults are decided by hashing ``(seed, stream, queue,
      index[, attempt])`` — a pure function of each message's identity, so
      two runs with the same seed inject bit-identical faults regardless of
      event-loop interleaving. (``BrokerConfig.drop_prob`` draws from one
      shared RNG whose call ORDER depends on scheduling — soak accounting
      under it is irreproducible by construction.)

    Engine step counters live in the app runtime (utils/chaos.py
    ``ChaosState``), not the engine, so indices keep advancing across
    engine revives — a schedule failing steps 0-2 trips the circuit breaker
    instead of re-failing step 0 on every fresh engine forever.
    """

    seed: int = 0
    #: Queues the broker faults apply to; () = every queue including reply
    #: queues. Name the request queues to keep reply traffic fault-free
    #: (response publishes interleave nondeterministically with requests,
    #: so scripting them by index is rarely what a test wants).
    queues: tuple[str, ...] = ()
    # ---- seeded broker faults (pure function of (queue, seq, attempt)) ----
    #: Consume-side drop probability: the delivery is "crashed" before
    #: processing and requeued, exactly like BrokerConfig.drop_prob but
    #: decided by hash(seed, queue, seq, attempt).
    drop_prob: float = 0.0
    #: Publish-side duplicate-delivery probability, hash-decided per seq.
    dup_prob: float = 0.0
    # ---- scripted broker faults (per-queue publish sequence indices) ------
    #: Publish seqs whose FIRST delivery attempt is dropped.
    drop_seqs: tuple[int, ...] = ()
    #: Redelivery storms: (seq, extra_copies) — that publish is delivered
    #: 1 + extra_copies times (dedup/idempotence must absorb the storm).
    dup_seqs: tuple[tuple[int, int], ...] = ()
    #: Broker partitions: [pause_seq, resume_seq) — consumers of the queue
    #: pause when publish seq ``pause_seq`` is enqueued and resume when
    #: ``resume_seq`` is (messages buffer meanwhile; at-least-once holds).
    partitions: tuple[tuple[int, int], ...] = ()
    #: Failsafe: a paused queue auto-resumes after this many seconds even if
    #: the resume-seq publish never arrives (a mis-scripted schedule must
    #: not wedge a drain forever; fault ACCOUNTING stays seq-deterministic).
    partition_max_s: float = 5.0
    # ---- scripted engine faults (per-queue device search-step indices) ----
    #: Device search-step indices that raise ChaosInjectedError at dispatch.
    fail_steps: tuple[int, ...] = ()
    #: Same, as [start, stop) ranges — "raise on k consecutive windows".
    fail_step_ranges: tuple[tuple[int, int], ...] = ()
    #: The first N half-open breaker probes fail (a separate stream from
    #: fail_steps, so probe outcomes are scriptable independently of how
    #: many traffic steps the storm consumed).
    fail_probes: int = 0
    #: Device-loss fault (ISSUE 15): device SEARCH-step indices at which
    #: the engine raises ``ChaosDeviceLostError`` — modeling a mesh
    #: participant dying mid-serve (the XLA "device lost / transfer
    #: failed" error class, which a revive-from-mirror cannot fix because
    #: the rebuilt engine would bind the same dead chip). Shares the
    #: per-queue step counter with ``fail_steps``. The queue runtime
    #: routes it through the breaker's crash accounting into the failover
    #: path: an elastic-shardable sharded queue demotes to its SURVIVING
    #: devices (D → D-1, journal/mirror as the pool source) instead of
    #: revive-looping the dead mesh; the demotion is audited at
    #: /debug/placement with the measured blackout.
    device_lost_steps: tuple[int, ...] = ()
    #: Which logical device of the queue's binding "died" (-1 = the last
    #: device — the default models losing the highest shard).
    device_lost_device: int = -1
    # ---- scripted replication-link faults (per-queue STREAM record seqs,
    # ---- ISSUE 17; consumed by service/replication.InProcReplicationLink.
    # ---- Scripted faults fire on a record's FIRST transmission only —
    # ---- retransmission of the unacked tail is how the stream heals) ----
    #: Stream record seqs whose first transmission is dropped.
    repl_drop_seqs: tuple[int, ...] = ()
    #: Stream record seqs delivered twice (the applier's dedup absorbs).
    repl_dup_seqs: tuple[int, ...] = ()
    #: Reordering: (seq, hold_n) — the record is held until ``hold_n``
    #: further first transmissions pass, then delivered LATE (the
    #: applier's gap buffer must absorb the out-of-order arrival).
    repl_delay_seqs: tuple[tuple[int, int], ...] = ()
    #: Link partitions: [pause_seq, resume_seq) — the stream buffers from
    #: the pause record's first transmission until ANY transmission
    #: reaches the resume seq (replication lag grows; the failover-soak's
    #: lag-bounded-loss gate exercises exactly this window).
    repl_partitions: tuple[tuple[int, int], ...] = ()
    #: Seeded stream drop probability, hash-decided per
    #: (seed, "repl", queue, seq) — reproducible like every seeded fault.
    repl_drop_prob: float = 0.0
    #: Scripted lease-expiry faults: global renewal-call indices the
    #: LeaseAuthority refuses — the deterministic way to make a LIVE
    #: primary's lease lapse so a standby can legally take over (the
    #: split-brain fencing regression rides this).
    repl_fail_renewals: tuple[int, ...] = ()
    # ---- scripted NETWORK faults (ISSUE 20; consumed by net/nemesis.py
    # ---- at the socket transport's send/recv seams. Every entry names a
    # ---- FLOW by substring match against the connection's flow id
    # ---- ("repl:<queue>:fwd", "repl:<queue>:ack", "lease:<owner>") and a
    # ---- data-frame seq — record seq on replication flows, a per-flow
    # ---- frame counter elsewhere — so every decision is a pure function
    # ---- of (seed, connection id, frame seq). Scripted faults fire on a
    # ---- frame's FIRST transmission only, like the repl_* family:
    # ---- retransmission by cumulative ack is how the stream heals) ----
    #: (flow substring, frame seq): first transmission is dropped.
    net_drop_frames: tuple[tuple[str, int], ...] = ()
    #: (flow substring, frame seq): first transmission is sent twice.
    net_dup_frames: tuple[tuple[str, int], ...] = ()
    #: (flow, seq, hold_n): frame held until ``hold_n`` further first
    #: transmissions pass, then sent LATE (reordering over the wire).
    net_delay_frames: tuple[tuple[str, int, int], ...] = ()
    #: (flow, seq): instead of sending the frame, the sender abruptly
    #: closes the connection mid-stream (the torn-stream case — resume is
    #: reconnect + cumulative-ack retransmission).
    net_reset_frames: tuple[tuple[str, int], ...] = ()
    #: (flow, pause_seq, resume_seq): sender-side partition window
    #: [pause, resume) — frames buffer at the sender until any
    #: transmission reaches the resume seq.
    net_partitions: tuple[tuple[str, int, int], ...] = ()
    #: Flows whose INBOUND frames this process drops from the start — the
    #: scripted ASYMMETRIC partition (a primary that can send but cannot
    #: hear acks or lease-renewal responses lists its ack + lease flows
    #: here; heartbeats are dropped too, so the liveness verdict sees it).
    net_deaf_flows: tuple[str, ...] = ()
    #: Seeded frame-drop probability, hash-decided per
    #: (seed, "net", flow, seq) — reproducible like every seeded fault.
    net_drop_prob: float = 0.0
    #: (flow, bytes_per_s): sender-side bandwidth cap — frames over the
    #: budget wait (delivery delay, never corruption).
    net_bandwidth_caps: tuple[tuple[str, int], ...] = ()

    def enabled(self) -> bool:
        return bool(
            self.drop_prob > 0 or self.dup_prob > 0 or self.drop_seqs
            or self.dup_seqs or self.partitions or self.fail_steps
            or self.fail_step_ranges or self.fail_probes
            or self.device_lost_steps
        )

    def consume_faults(self) -> bool:
        """Any consume-side broker fault configured? (broker hot-path gate)"""
        return bool(self.drop_prob > 0 or self.drop_seqs)

    def publish_faults(self) -> bool:
        """Any publish-side broker fault configured? (broker hot-path gate)"""
        return bool(self.dup_prob > 0 or self.dup_seqs or self.partitions)

    def replication_faults(self) -> bool:
        """Any replication-link fault configured? (read by the hub when
        building links — the broker/engine gates above are untouched)."""
        return bool(
            self.repl_drop_seqs or self.repl_dup_seqs or self.repl_delay_seqs
            or self.repl_partitions or self.repl_drop_prob > 0
            or self.repl_fail_renewals
        )

    def net_faults(self) -> bool:
        """Any socket-transport fault configured? (read by net/nemesis.py
        when building per-flow fault scripts — the broker/engine/repl
        gates above are untouched)."""
        return bool(
            self.net_drop_frames or self.net_dup_frames
            or self.net_delay_frames or self.net_reset_frames
            or self.net_partitions or self.net_deaf_flows
            or self.net_drop_prob > 0 or self.net_bandwidth_caps
        )


@dataclass(frozen=True)
class OverloadConfig:
    """End-to-end overload control (service/overload.py): per-queue
    admission control, deadline propagation, adaptive load shedding, and
    graceful drain/handoff. The reference's survival story under load is
    RabbitMQ buffering — queues grow without bound and clients that gave
    up keep consuming engine windows; this subsystem bounds the queue in
    front of the matcher and is honest about rejection (explicit ``shed``
    responses with retry-after hints, never silent rot).

    Every knob is deterministic: admission decisions are pure functions of
    the controller's credit/pool counts at the decision point, so a chaos
    soak with burst ingress replays bit-identically (tests/test_overload).
    """

    #: Token/credit limiter: max admitted-but-unsettled deliveries per
    #: queue (a credit is held from admission until the delivery's ack or
    #: nack). 0 = unlimited. Also bounds the broker consumer's prefetch.
    max_inflight: int = 0
    #: Max waiting-pool occupancy counted at admission (live pool size +
    #: admitted credits on their way into it). 0 = unlimited.
    max_waiting: int = 0
    #: What to shed when the waiting cap is hit: ``"reject"`` sheds the
    #: INCOMING request (cheapest — nothing decoded, nothing dispatched);
    #: ``"oldest"`` admits it and sheds the longest-waiting pool player
    #: instead (freshness-biased queues, e.g. quick-play).
    shed_policy: str = "reject"
    #: Retry-after hint (ms) carried on shed responses — clients back off
    #: instead of hammering an overloaded queue.
    retry_after_ms: float = 1000.0
    #: Deadline propagation: requests arriving WITHOUT an ``x-deadline``
    #: header get one stamped at admission, first-received + this budget
    #: (0 = don't stamp; client-stamped deadlines are always honored).
    #: Deadlines are checked at admission, batch formation, and
    #: pre-dispatch — an expired request is cancelled (``timeout``
    #: response, ``expired`` trace mark) before any device work is spent.
    #: Transport caveat (same as ``x-first-received``): consumer-side
    #: stamps survive redelivery on the in-proc broker (the Delivery
    #: object is reused) but NOT over real AMQP, where a nack-requeue
    #: redelivers the originally PUBLISHED headers — a crash-looping
    #: request then gets a fresh budget per attempt. Clients that need a
    #: hard end-to-end deadline over AMQP must stamp it themselves at
    #: publish (``MatchmakingClient.submit(deadline_s=...)``), which is
    #: immune: publish-time headers do survive the wire and redelivery.
    default_deadline_ms: float = 0.0
    #: QoS priority classes (Nitsum admission tiers): requests carry an
    #: ``x-tier`` header (0 = most latency-critical; missing header → the
    #: queue's ``default_tier``), and admission partitions every cap into a
    #: nested ladder so graceful degradation is ORDERED — the lowest tier
    #: absorbs shedding and queueing first, and tier 0 is untouched until
    #: every lower tier is exhausted. 1 = untiered (exactly the pre-tier
    #: behavior; zero per-delivery overhead beyond one header default).
    tiers: int = 1
    #: Fraction of each cap tier ``t`` may reach counting only SAME-OR-
    #: HIGHER-priority usage (tiers ``<= t``): tier t is shed once
    #: occupancy(tiers 0..t) >= cap * tier_shares[t]. Element 0 is forced
    #: to 1.0 (tier 0 may use the whole cap); () → the equal ladder
    #: ((tiers-t)/tiers). Monotone non-increasing by construction of the
    #: check: a LOWER tier stops admitting strictly earlier, which is what
    #: makes adaptive tightening consume tier-2 first — every cap scales
    #: by the credit fraction and the smallest slice binds first.
    tier_shares: tuple[float, ...] = ()
    #: Earliest-deadline-first window cutting: the batcher and the columnar
    #: flush order window candidates by (tier, absolute x-deadline) instead
    #: of arrival order, so a near-deadline tier-0 request dispatches in
    #: the next device window instead of behind the backlog. Stable within
    #: equal keys (FIFO preserved for untiered/undeadlined traffic).
    edf: bool = False
    #: Pool-resident deadline expiry: sweep the per-slot ``x-deadline``
    #: column of every waiting pool this often (ms; 0 = off) and cancel
    #: expired waiters EXACTLY at their deadline — ``timeout`` response,
    #: ``expired`` trace mark, no dispatch — instead of the coarse
    #: ``request_timeout_s`` sweeper granularity.
    deadline_sweep_ms: float = 0.0
    #: Adaptive shedding: tighten the credit limit from live signals
    #: (pipeline occupancy, batch fill, per-stage p99) so the limiter
    #: reacts BEFORE the circuit breaker trips.
    adaptive: bool = False
    #: Adaptive target: when the queue's end-to-end stage p99 exceeds this,
    #: the effective credit limit is multiplied by ``tighten_step``; when
    #: p99 falls below half the target and the pipeline has headroom it is
    #: relaxed by ``relax_step`` (clamped to [min_credit_fraction, 1.0]).
    target_p99_ms: float = 250.0
    min_credit_fraction: float = 0.25
    tighten_step: float = 0.5
    relax_step: float = 1.25
    #: Graceful drain/handoff: SIGTERM (service.app.serve) stops admission,
    #: drains in-flight windows, and checkpoints every queue's waiting pool
    #: into this directory (utils/checkpoint.py); a restarted app restores
    #: it — zero waiting players lost. "" = drain without checkpointing.
    drain_checkpoint_dir: str = ""
    #: Window-granular admission (ISSUE 9): run the credit/occupancy ladder
    #: ONCE per cut window inside the flush (arrival-order pass over the
    #: window's cached tier/deadline columns) instead of per delivery at
    #: ingress. The per-delivery ingress keeps only the pre-checks that
    #: cannot wait for a cut (already-expired-at-receive, drain-mode shed,
    #: tier/deadline header caching for the EDF cut key). Ladder semantics
    #: are identical over the same count sequence — batching never reorders
    #: decisions within the stream. False = the per-delivery PR 5/7 path,
    #: byte for byte.
    batch_admission: bool = True

    def enabled(self) -> bool:
        """Any admission/deadline/drain machinery configured? The ingress
        hot path pays zero per-delivery overhead when False.
        ``drain_checkpoint_dir`` alone counts: the drain sequence needs a
        controller to flip into shed-everything mode (and /healthz needs
        it to report ``draining``) even when no cap is set. ``tiers > 1``
        and ``deadline_sweep_ms`` count too: tier parsing/accounting and
        the per-slot deadline sweep ride the controller."""
        return bool(self.max_inflight > 0 or self.max_waiting > 0
                    or self.default_deadline_ms > 0 or self.adaptive
                    or self.drain_checkpoint_dir or self.tiers > 1
                    or self.deadline_sweep_ms > 0)


@dataclass(frozen=True)
class DurabilityConfig:
    """Crash durability (ISSUE 15; utils/journal.py): a per-queue
    write-ahead pool journal + periodic compaction snapshots, so a HARD
    crash (OOM, host loss, ``kill -9``) recovers the waiting pool, the
    at-least-once dedup/replay cache and the admission decision state —
    the graceful drain→checkpoint→restore round trip (OverloadConfig.
    drain_checkpoint_dir) only fires on SIGTERM.

    Mechanics: admit/match/evict/expire mutations append as CRC-framed,
    version-stamped records, batched per cut window (the hot columnar
    path pays ONE buffered append per window, not per player) and
    committed before the corresponding response/ack leaves (write-ahead:
    a matched response is never visible before its terminal record is).
    The live segment periodically compacts into a pool snapshot
    (utils/checkpoint format) + a fresh segment; boot detects an unclean
    shutdown (no clean-shutdown marker) and replays newest-valid
    snapshot + journal tail into the engine — recovery time recorded as
    the ``crash_rto_ms`` gauge and a ``crash_recovered`` EventLog event.
    """

    #: Directory for per-queue journal segments + compaction snapshots
    #: ("" = durability off: zero hot-path work, no files).
    journal_dir: str = ""
    #: Commit durability: ``"none"`` buffers through the OS page cache
    #: (cheapest; a HOST loss can drop the tail, a process crash cannot),
    #: ``"interval"`` fsyncs at most every ``fsync_interval_s`` seconds,
    #: ``"window"`` fsyncs every commit (= every cut window — the
    #: bounded-loss setting the crash-soak acceptance measures).
    fsync: str = "none"
    #: Max seconds between fsyncs under the ``"interval"`` policy.
    fsync_interval_s: float = 0.05
    #: Compact (snapshot + segment rotation) once the live segment holds
    #: this many records…
    compact_records: int = 50_000
    #: …or this many bytes, whichever first. Compaction runs off the hot
    #: path (the app's durability timer), under the engine lock with the
    #: pipeline drained, so the snapshot is exactly consistent with the
    #: journal sequence it anchors.
    compact_bytes: int = 8 << 20
    #: Compaction-check cadence for the durability timer (seconds).
    compact_interval_s: float = 1.0
    #: Snapshot generations retained per queue (newest + fallbacks): a
    #: truncated/corrupt newest snapshot falls back to the previous good
    #: one at recovery instead of crashing the boot.
    keep_snapshots: int = 2

    def enabled(self) -> bool:
        return bool(self.journal_dir)


@dataclass(frozen=True)
class ReplicationConfig:
    """Hot-standby journal replication + fenced cross-host failover
    (ISSUE 17, service/replication.py). The primary streams every sealed
    WAL record per queue over a pluggable link to a warm standby that
    applies them into a shadow pool/dedup/admission state and acks a
    replication watermark; failover is lease/epoch-fenced (the standby
    takes over only after lease expiry, bumps the epoch, and the
    ex-primary's appends and publishes are refused at the journal-append
    and response-publish seams). Requires durability (the WAL is the
    stream source) and a :class:`~matchmaking_tpu.service.replication.
    ReplicationHub` passed to ``MatchmakingApp(replication_hub=...)`` —
    the hub is the in-process stand-in for the cross-host fabric (links
    + lease service), so config alone cannot conjure a standby."""

    #: ``""`` = replication off (zero hot-path work: no journal tap, no
    #: fence checks, no pump task). ``"primary"`` = this app streams and
    #: serves. (The standby side is not a full app — it is the hub's
    #: StandbyApplier, promoted via takeover + successor adoption.)
    role: str = ""
    #: This host's lease identity. A failover successor must boot with
    #: the TAKEOVER owner (the standby identity that bumped the epoch) —
    #: acquire() by the current lease holder renews; by anyone else over
    #: an unexpired lease it refuses (split-brain guard at boot).
    owner: str = "primary"
    #: Sender pump cadence (seconds): ack collection, stall retransmit,
    #: lease renewal, lag gauges.
    pump_interval_s: float = 0.02

    def enabled(self) -> bool:
        if self.role and self.role != "primary":
            raise ValueError(
                f"unknown replication role {self.role!r} (\"\" or "
                f"\"primary\"; the standby is a hub-side StandbyApplier, "
                f"not an app role)")
        return bool(self.role)


@dataclass(frozen=True)
class NetConfig:
    """Real-transport DCN seams (ISSUE 20, matchmaking_tpu/net/): the
    framed socket transport under the replication link and the lease
    service. ``transport="socket"`` makes the replication fabric run over
    TCP/UDS — length-prefixed CRC-framed messages, application
    heartbeats with a deadline-based peer-liveness verdict, seeded
    exponential-backoff-with-jitter reconnect, and bounded send buffers
    that surface backpressure (a dropped frame is healed by the
    cumulative-ack retransmission the in-proc link already relies on).

    Addresses are ``"unix:/path.sock"`` or ``"tcp:host:port"``. The
    fencing-over-RTT rule lives here too: ``lease_rtt_budget_s`` is
    subtracted from every lease grant the :class:`~matchmaking_tpu.net.
    lease.RemoteLeaseAuthority` caches, so a renewal still in flight when
    the budgeted deadline passes does NOT count — safety over liveness."""

    #: "inproc" (default — the PR 17 in-process fabric, zero sockets) or
    #: "socket" (the real transport; an app with replication enabled and
    #: no hub passed builds a SocketReplicationHub from the addrs below).
    transport: str = "inproc"
    #: Lease service address (required for transport="socket").
    lease_addr: str = ""
    #: Where this primary streams replication records (the standby's
    #: listen address). One queue per address; "" = stream to nowhere
    #: (frames drop until a target is set on the hub).
    repl_target: str = ""
    #: Dial timeout per connect attempt (seconds).
    connect_timeout_s: float = 1.0
    #: Blocking lease-RPC timeout (acquire/takeover/expired/release and
    #: the expired-validity renew re-confirm).
    request_timeout_s: float = 1.0
    #: Application heartbeat cadence per connection.
    heartbeat_interval_s: float = 0.1
    #: Peer-liveness deadline: no inbound frame for this long → the peer
    #: is declared dead (counted; the connection closes and reconnects).
    heartbeat_timeout_s: float = 0.6
    #: Reconnect backoff: min(cap, base * 2^attempt) scaled by seeded
    #: jitter in [0.5, 1.0] — hash01(seed, "backoff", conn, attempt).
    reconnect_base_s: float = 0.02
    reconnect_cap_s: float = 1.0
    #: Hostile-length guard: a frame header announcing more than this is
    #: a FrameError (connection dies; stream resumes by ack).
    max_frame_bytes: int = 1 << 20
    #: Bounded send buffer per link: once this many bytes are queued or
    #: in the transport buffer, further sends DROP and count
    #: (backpressure_dropped) instead of buffering unboundedly.
    send_buffer_bytes: int = 4 << 20
    #: Subtracted from every cached lease grant: the client treats a
    #: lease granted at send-time t as valid until t + lease_s - budget,
    #: under-approximating the authority's own deadline by the RTT the
    #: request may have spent in flight.
    lease_rtt_budget_s: float = 0.05

    def enabled(self) -> bool:
        if self.transport not in ("", "inproc", "socket"):
            raise ValueError(
                f"unknown net transport {self.transport!r} "
                f"(\"inproc\" or \"socket\")")
        return self.transport == "socket"


@dataclass(frozen=True)
class PlacementConfig:
    """Elastic queue→device placement control plane (matchmaking_tpu/
    control/): a controller that watches the telemetry ring (per-queue SLO
    burn, device idle fraction, effective occupancy, stage p99) and
    live-migrates queues across device engines using the drain/checkpoint/
    restore primitive — plus Nitsum-style elastic sharding (promote a hot
    1v1 queue from single-chip to D>1 and back as load recedes).  The
    greedy burn-to-idle policy ships first; the policy seam
    (control/policy.PlacementPolicy) is where a MIPS-style search planner
    drops in later.

    Every decision is a pure function of the controller's signal view at
    the tick (no RNG, no clock reads inside the policy — ``now`` is data),
    so the seeded simulation mode (control/simulate.py) replays decision
    traces bit-identically without devices."""

    #: Controller tick interval (seconds; 0 disables the control plane
    #: entirely — no task, no arbiter, zero hot-path overhead).
    interval_s: float = 0.0
    #: Logical device inventory the controller places queues onto. 0 =
    #: discover from the live backend (``jax.devices()``); N > 0 = a fixed
    #: logical inventory — what the host-oracle backend and the seeded
    #: simulation use (CpuEngine carries placement as metadata only).
    devices: int = 0
    #: A queue is HOT (migration source) when its SLO is burning or its
    #: device idle fraction over the last telemetry window falls below
    #: this bound.
    hot_idle_below: float = 0.15
    #: A device is a migration TARGET only when its idle fraction exceeds
    #: this bound (and it hosts no hot queue).
    cold_idle_above: float = 0.5
    #: Minimum idle-fraction gap between target and source devices before
    #: a migration is worth its blackout.
    min_idle_gain: float = 0.2
    #: Per-queue cooldown between placement actions (seconds) — bounds
    #: migrate/promote thrash; measured against the tick's ``now``.
    cooldown_s: float = 10.0
    #: Elastic sharding cap for 1v1 device queues (Nitsum adaptive
    #: parallelism): a hot queue alone on its device may be promoted to up
    #: to this many chips (D>1, engine/sharded.py) and is demoted back as
    #: load recedes. 1 = no elastic sharding.
    max_shard: int = 1
    #: Promote only while effective occupancy (valid/padded lanes) exceeds
    #: this — an idle-but-burning queue gains nothing from more chips.
    promote_occupancy: float = 0.5
    #: Demote a sharded queue once its idle fraction exceeds this.
    demote_idle_above: float = 0.8
    #: Placement decisions kept in the audit ring (/debug/placement).
    decision_ring: int = 256
    #: Cross-queue (tier, deadline) dispatch arbitration for queues the
    #: controller co-locates on one device: EDF ordering holds ACROSS
    #: co-located queues' concurrently-waiting windows, not just within
    #: one batcher.  Only engaged while >= 2 queues share a device — an
    #: unshared device's dispatches bypass the arbiter entirely.
    arbiter: bool = True

    def enabled(self) -> bool:
        return self.interval_s > 0


@dataclass(frozen=True)
class AutotuneConfig:
    """Telemetry-driven online autotuner (matchmaking_tpu/control/
    autotune.py, ISSUE 13): a supervised tick loop — the same audited
    decision shape as the placement controller — that reads the telemetry
    ring (stage p99, batch fill, idle fraction, shed deltas) and the SLO
    burn monitors, and moves ONE serving knob per tick within the declared
    safe ranges below:

    - ``max_wait_ms`` (the batcher window wait) — tightened multiplicatively
      while the queue's p99 exceeds the target; NEVER widened back by the
      tuner (a one-way ratchet: widening trades latency for batch fill,
      a tradeoff the frontier bench owns, not an online controller).
    - ``edf`` — earliest-deadline-first window cutting switched ON for a
      burning queue whose deliveries carry deadlines (also a ratchet).
    - ``pipeline_depth`` — in-flight window cap stepped down when latency
      stays high after the window floor, stepped back up once calm.
    - ``credit_fraction`` — the admission credit scale stepped down so an
      overloaded queue sheds earlier with honest responses; stepped back
      toward 1.0 once calm. Skipped when ``OverloadConfig.adaptive`` is on
      (that controller owns the fraction — two writers would fight).

    Safety model: every move is clamped to the range knobs below, applied
    one per tick so each effect is observable before the next decision,
    and recorded — trigger signals, from→to, observed effect one tick
    later — in a bounded audit ring served at ``/debug/autotune``. The
    plan step is a pure function of the signal view (no RNG, no clock
    reads), so a deterministic signal trajectory replays a bit-identical
    decision trace (tests/test_autotune.py pins it)."""

    #: Tick interval (seconds; 0 disables — no task, no knob writes).
    interval_s: float = 0.0
    #: The latency target the tuner steers to: tighten while the queue's
    #: rolling stage-total p99 exceeds this, relax when it falls below
    #: half of it. 0 → inherit ``ObservabilityConfig.slo_target_ms``.
    target_p99_ms: float = 0.0
    #: Safe range for the batcher window wait.
    max_wait_ms_min: float = 0.5
    max_wait_ms_max: float = 50.0
    #: Safe range for the pipeline depth (upper bound additionally clamped
    #: to the engine's configured ``pipeline_depth``).
    pipeline_depth_min: int = 1
    #: Floor for the admission credit fraction (the controller's own
    #: ``min_credit_fraction`` still applies; the tighter bound wins).
    credit_fraction_min: float = 0.25
    #: Multiplicative steps (tighten < 1 < relax).
    wait_step: float = 0.5
    fraction_step: float = 0.8
    #: Ticks a queue must stay calm (p99 < target/2, not burning) before a
    #: relax move, and the minimum ticks between ANY two moves on one
    #: queue — each move's effect must land in the telemetry ring before
    #: the next decision reads it.
    settle_ticks: int = 2
    #: Decisions kept in the audit ring (/debug/autotune).
    decision_ring: int = 256

    def enabled(self) -> bool:
        return self.interval_s > 0


@dataclass(frozen=True)
class ObservabilityConfig:
    """Request-lifecycle flight recorder + debug surfaces (utils/trace.py,
    service/observability.py). The BASELINE north star asserts a p99;
    these knobs size the machinery that explains one: trace contexts are
    stamped at broker publish, carried through every stage, and settled
    into per-queue rings + true per-stage histograms."""

    #: Trace-context stamping + flight recording. On by default: the cost
    #: is one small object per publish and O(marks) appends per delivery —
    #: measured noise next to decode/publish work.
    trace: bool = True
    #: Trace every Nth request publish (1 = every publish, the default).
    #: At the measured service knee one context per publish is noise, but
    #: a 500k+/s ingress allocates half a million dead objects a second
    #: for rings that keep 256 — sample instead: stage histograms stay
    #: statistically true, exemplars stay available, and untraced
    #: deliveries skip every mark. Applies to broker-side stamping (in-proc
    #: AND the AMQP header stamp); the lazy ingress fallback only runs at
    #: N == 1 so sampled-out deliveries aren't resurrected downstream.
    trace_sample_n: int = 1
    #: Completed traces kept per queue (newest wins; bounded memory).
    trace_ring: int = 256
    #: Slow-trace exemplars kept per queue.
    slow_trace_ring: int = 64
    #: A settled trace whose enqueue→publish span exceeds this keeps its
    #: full stage breakdown in the slow ring (/debug/traces "slow").
    slow_trace_ms: float = 250.0
    #: Lifecycle event-log ring size (/debug/events): breaker trips,
    #: probes, delegations, re-promotions, revives, chaos faults.
    event_ring: int = 512
    #: Per-stage histogram bucket upper bounds in SECONDS; () → the
    #: default log-spaced scheme (utils/metrics.DEFAULT_STAGE_BUCKETS:
    #: 100 µs · 2^k, 24 buckets + overflow, topping out ~14 min).
    stage_buckets: tuple[float, ...] = ()
    #: Where /debug/profile?secs=N writes its jax.profiler capture;
    #: "" → a fresh temp directory per capture.
    profile_dir: str = ""
    #: Continuous telemetry (utils/timeseries.TelemetryRing): the app
    #: samples a snapshot of per-queue signals (pool size, batch fill,
    #: breaker state, shed/expired totals, device busy/idle counters,
    #: stage p99, SLO good/total) every this many seconds into a bounded
    #: in-proc ring with delta/rate queries — the load signal the elastic
    #: placement controller (ROADMAP) consumes. 0 disables the sampler.
    snapshot_interval_s: float = 1.0
    #: Snapshots kept in the telemetry ring (newest wins).
    telemetry_ring: int = 512
    #: Per-queue SLO monitoring (utils/timeseries.SloMonitor): a settled
    #: request is GOOD when it reached a served outcome (matched / queued /
    #: deduped — shed and expired burn the budget on purpose) within this
    #: many milliseconds end to end (enqueue→publish). 0 disables SLO
    #: accounting and the burn monitors entirely.
    slo_target_ms: float = 0.0
    #: Attainment objective: the fraction of requests that must be GOOD
    #: (0.99 = "99% of requests served within the target").
    slo_objective: float = 0.99
    #: Multi-window burn-rate evaluation: the FAST window detects a budget
    #: bleed quickly, the SLOW window de-flaps; the queue is declared
    #: burning (``slo_burn`` EventLog event, ``slo_burning`` gauge,
    #: /healthz ``slo``) only when BOTH windows' burn rates exceed
    #: ``slo_burn_threshold``.
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 300.0
    slo_burn_threshold: float = 1.0
    #: Match-quality & fairness observatory (ISSUE 8; engine/quality.py).
    #: Rating-bucket edges for the conditional quality/wait accounting —
    #: () → engine/quality.DEFAULT_RATING_EDGES (8 buckets around a
    #: N(1500, 300) rating distribution). The fairness axis: per-bucket
    #: conditional means + the disparity gaps are computed over these.
    quality_rating_edges: tuple[float, ...] = ()
    #: Linear quality-histogram buckets over [0, 1].
    quality_buckets: int = 20
    #: Wait-at-match histogram bucket upper bounds (seconds); () → the
    #: default log-spaced scheme (1 ms · 2^k, 22 buckets + overflow).
    quality_wait_buckets: tuple[float, ...] = ()
    #: Device-accumulator readback cadence, in WINDOWS: the engine
    #: snapshots its device-resident quality state with an async D2H every
    #: N finalized windows and materializes it at a later finalize — the
    #: quality report is at most N windows stale and the hot path never
    #: pays a synchronous readback. flush() forces a fresh snapshot.
    quality_report_every: int = 16
    #: Per-queue quality SLO (reuses utils/timeseries.SloMonitor): a
    #: matched player is GOOD when the match quality is ≥ this target
    #: (0..1; 0 disables). Quality regressions then burn on /healthz
    #: exactly like latency SLOs — ``<queue>#quality`` monitor keys.
    quality_slo_target: float = 0.0
    #: Fraction of matched players that must meet the quality target.
    quality_slo_objective: float = 0.9


@dataclass(frozen=True)
class ForensicsConfig:
    """Incident forensics (ISSUE 18; utils/forensics.py): the causal
    event spine every lifecycle emission is stamped onto, plus the
    black-box auto-capture that freezes ring snapshots into
    schema-versioned incident bundles on trigger rules (SLO burn start,
    breaker trip, failover takeover, crash recovery, migration blackout
    over budget, autotuner oscillation). Surfaced at /debug/incidents."""

    #: Spine events kept in the process-wide causal ring.
    spine_ring: int = 4096
    #: Master switch for auto-capture (the spine itself always runs —
    #: it is the EventLog's ordering substrate and costs one counter).
    capture: bool = True
    #: Where bundles are persisted as JSON; "" keeps them in-proc only
    #: (/debug/incidents still serves the bounded ring).
    incident_dir: str = ""
    #: Bundles kept in the in-proc ring (newest wins).
    incident_ring: int = 16
    #: Bundle FILES kept under incident_dir (oldest pruned).
    retention_files: int = 32
    #: Per-trigger-class minimum seconds between captures — the burn-storm
    #: damper. Dropped captures are counted (incidents_dropped), never
    #: silent.
    min_interval_s: float = 5.0
    #: Spine events frozen per bundle (the incident window).
    spine_window: int = 512
    #: Telemetry-ring snapshots frozen per bundle.
    telemetry_tail: int = 32
    #: Slow-trace exemplars frozen per queue per bundle.
    trace_slice: int = 8
    #: Placement/autotune audit records frozen per bundle.
    audit_slice: int = 32
    #: Migration blackout budget (ms): a completed placement action whose
    #: measured blackout exceeds this triggers a capture. 0 disables the
    #: blackout trigger.
    blackout_budget_ms: float = 0.0
    #: Knob moves remembered per (queue, knob) for the autotuner
    #: oscillation detector (src→dst then dst→src within this window).
    oscillation_window: int = 8

    def enabled(self) -> bool:
        return self.capture


@dataclass(frozen=True)
class BatcherConfig:
    """Request windowing: collect a batch per queue, dispatch one kernel."""

    max_batch: int = 1024
    max_wait_ms: float = 5.0


@dataclass(frozen=True)
class AuthConfig:
    """Auth middleware. The reference checks each request's token against the
    platform's ``microservice-auth`` over AMQP RPC (SURVEY.md §2 C5); here the
    verifier is pluggable: ``"none"`` (off), ``"static"`` (shared-secret
    prefix), or ``"rpc"`` (round-trip over the broker to an auth queue)."""

    mode: str = "none"
    static_secret: str = "open-matchmaking"
    rpc_queue: str = "auth.token.verify"
    rpc_timeout_ms: float = 250.0


@dataclass(frozen=True)
class Config:
    queues: tuple[QueueConfig, ...] = (QueueConfig(),)
    engine: EngineConfig = field(default_factory=EngineConfig)
    broker: BrokerConfig = field(default_factory=BrokerConfig)
    batcher: BatcherConfig = field(default_factory=BatcherConfig)
    auth: AuthConfig = field(default_factory=AuthConfig)
    #: Deterministic fault-injection schedule (off by default — every field
    #: zero/empty means no chaos plumbing is touched on the hot path).
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    #: Admission control / load shedding / deadline propagation / graceful
    #: drain (off by default — see OverloadConfig.enabled()).
    overload: OverloadConfig = field(default_factory=OverloadConfig)
    #: Crash durability: write-ahead pool journal + hard-crash recovery
    #: (off by default — see DurabilityConfig.enabled()).
    durability: DurabilityConfig = field(default_factory=DurabilityConfig)
    #: Hot-standby journal replication + fenced failover (off by default
    #: — see ReplicationConfig.enabled(); requires durability).
    replication: ReplicationConfig = field(default_factory=ReplicationConfig)
    #: Real-transport DCN seams: socket replication link + remote lease
    #: service (ISSUE 20; "inproc" by default — zero sockets).
    net: NetConfig = field(default_factory=NetConfig)
    #: Flight recorder / debug endpoints (tracing on by default).
    observability: ObservabilityConfig = field(
        default_factory=ObservabilityConfig)
    #: Incident forensics: causal event spine + black-box bundle capture
    #: (ISSUE 18; spine always on, capture on by default).
    forensics: ForensicsConfig = field(default_factory=ForensicsConfig)
    #: Elastic queue→device placement control plane (off by default — see
    #: PlacementConfig.enabled()).
    placement: PlacementConfig = field(default_factory=PlacementConfig)
    #: Telemetry-driven online autotuner (off by default — see
    #: AutotuneConfig.enabled()).
    autotune: AutotuneConfig = field(default_factory=AutotuneConfig)
    #: Number of concurrent search workers draining batches (the reference's
    #: GenServer pool size analog — SURVEY.md §2 C7).
    workers: int = 2
    seed: int = 0
    #: Online invariant checking (no player in two matches — SURVEY.md §5
    #: "Race detection"). One dict op per matched player; on in tests.
    debug_invariants: bool = False
    #: Optional HTTP observability endpoint (0 disables).
    metrics_port: int = 0
    #: Bind host for the observability endpoint. The localhost default is
    #: safe for bare-metal; containers must set ``0.0.0.0`` (the Dockerfile
    #: does) or docker-compose port mappings can't reach /metrics.
    metrics_host: str = "127.0.0.1"

    # ---- loading -----------------------------------------------------------

    @staticmethod
    def from_dict(d: Mapping[str, Any]) -> "Config":
        kw: dict[str, Any] = {}
        if "queues" in d:
            kw["queues"] = tuple(
                QueueConfig(**{**q, "role_slots": tuple(q.get("role_slots", ()))})
                for q in d["queues"]
            )
        for name, cls in (
            ("engine", EngineConfig),
            ("broker", BrokerConfig),
            ("batcher", BatcherConfig),
            ("auth", AuthConfig),
            ("chaos", ChaosConfig),
            ("overload", OverloadConfig),
            ("durability", DurabilityConfig),
            ("replication", ReplicationConfig),
            ("net", NetConfig),
            ("observability", ObservabilityConfig),
            ("forensics", ForensicsConfig),
            ("placement", PlacementConfig),
            ("autotune", AutotuneConfig),
        ):
            if name in d:
                sub = dict(d[name])
                # Tolerate unknown/removed keys (e.g. engine.use_pallas,
                # removed round 4) instead of failing the whole boot: a
                # config written for an older build should degrade to a
                # warning, not a TypeError at startup.
                known = {f.name for f in dataclasses.fields(cls)}
                for extra in [k for k in sub if k not in known]:
                    import logging

                    logging.getLogger(__name__).warning(
                        "config: ignoring unknown %s.%s (removed or "
                        "misspelled)", name, extra)
                    del sub[extra]
                def tuplify(v: Any) -> Any:
                    # Recursive: chaos dup_seqs/partitions/fail_step_ranges
                    # are tuples OF tuples in JSON ([[seq, n], ...]).
                    return (tuple(tuplify(x) for x in v)
                            if isinstance(v, list) else v)

                for f in dataclasses.fields(cls):
                    if f.name in sub and isinstance(sub[f.name], list):
                        sub[f.name] = tuplify(sub[f.name])
                kw[name] = cls(**sub)
        for scalar in ("workers", "seed", "debug_invariants", "metrics_port",
                       "metrics_host"):
            if scalar in d:
                kw[scalar] = d[scalar]
        return Config(**kw)

    @staticmethod
    def from_json(path: str) -> "Config":
        with open(path) as f:
            return Config.from_dict(json.load(f))

    @staticmethod
    def from_env(prefix: str = "MM_") -> "Config":
        """Env-var overrides of the flat scalar knobs (reference parity for
        12-factor config; nested keys use ``MM_ENGINE_BACKEND`` style).

        Two structural keys serve the multi-process supervisor
        (service/multiproc.py) and are generally useful:

        - ``MM_CONFIG_JSON=<path>`` — load the FULL config tree from a JSON
          file first, then apply the other ``MM_*`` scalars on top (env
          wins — the supervisor overrides per-worker backend/ports this
          way).
        - ``MM_QUEUE_NAMES=a,b`` — serve only the named queues from that
          tree (a worker's partition).
        """
        env = {k[len(prefix):].lower(): v for k, v in os.environ.items() if k.startswith(prefix)}
        base: dict[str, Any] = {}
        if "config_json" in env:
            with open(env.pop("config_json")) as f:
                base = json.load(f)
        queue_names = env.pop("queue_names", None)
        if not env and not base and queue_names is None:
            return Config()
        d: dict[str, Any] = base
        for key, raw in env.items():
            try:
                val: Any = json.loads(raw)
            except (ValueError, json.JSONDecodeError):
                val = raw
            if key in ("workers", "seed", "debug_invariants", "metrics_port",
                       "metrics_host"):
                d[key] = val
                continue
            parts = key.split("_", 1)
            if len(parts) != 2:
                continue
            section, name = parts
            d.setdefault(section, {})[name] = val
        cfg = Config.from_dict(d)
        if queue_names is not None:
            names = [n for n in str(queue_names).split(",") if n]
            keep = tuple(q for q in cfg.queues if q.name in names)
            missing = set(names) - {q.name for q in keep}
            if missing:
                raise KeyError(f"MM_QUEUE_NAMES not in config: {sorted(missing)}")
            cfg = dataclasses.replace(cfg, queues=keep)
        return cfg

    def to_dict(self) -> dict[str, Any]:
        """JSON-ready tree (inverse of from_dict; tuples become lists)."""
        return dataclasses.asdict(self)

    def queue(self, name: str) -> QueueConfig:
        for q in self.queues:
            if q.name == name:
                return q
        raise KeyError(f"unknown queue {name!r}")
