"""Deterministic network nemesis (ISSUE 20).

Rides the ChaosConfig ``net_*`` vocabulary the way the in-proc
replication link rides ``repl_*``: every fault decision is a pure
function of (seed, connection/flow id, frame seq) — scripted entries
match flows by substring ("repl:<queue>:fwd", "repl:<queue>:ack",
"lease:<owner>") and fire on a frame's FIRST transmission only, so
retransmission of the unacked tail is how a faulted stream converges,
and two seeded runs inject bit-identical faults.

Sender-side verdicts (:class:`FlowNemesis.transmit`): drop, duplicate,
delay-by-N-transmissions (reordering), partition windows, mid-stream
connection RESET, and a bandwidth cap (pacing — frames wait, never
corrupt). Receiver-side (:meth:`NetNemesis.rx_deaf`): ASYMMETRIC
partitions — the case the in-proc link cannot express — where a process
keeps sending but its INBOUND frames (acks, lease responses, heartbeats)
vanish, either scripted from boot (``net_deaf_flows``) or armed at a
deterministic point by the soak driver (:meth:`NetNemesis.deafen`, the
runtime twin of ``InProcReplicationLink.partition``).
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from matchmaking_tpu.utils.chaos import hash01

__all__ = ["FlowNemesis", "NetNemesis"]


class FlowNemesis:
    """Sender-side fault pipeline for ONE flow. ``transmit(seq, frame)``
    returns the ordered actions the transport must take: zero or more
    ``("send", frame)`` and at most one ``("reset",)`` — delayed and
    partitioned frames are held inside and released by later
    transmissions, mirroring ``InProcReplicationLink.send`` exactly."""

    def __init__(self, flow: str, chaos: Any, seed: int,
                 count: "Callable[[str], None]"):
        self.flow = flow
        self._seed = int(seed)
        self._count = count

        def match2(entries) -> "dict[int, Any]":
            return {int(s): True for f, s in (entries or ()) if f in flow}

        self._drop = frozenset(match2(getattr(chaos, "net_drop_frames", ())))
        self._dup = frozenset(match2(getattr(chaos, "net_dup_frames", ())))
        self._reset = set(match2(getattr(chaos, "net_reset_frames", ())))
        self._delay = {int(s): int(h)
                       for f, s, h in (getattr(chaos, "net_delay_frames",
                                               ()) or ()) if f in flow}
        self._partitions = [(int(a), int(b))
                            for f, a, b in (getattr(chaos, "net_partitions",
                                                    ()) or ()) if f in flow]
        self._drop_prob = float(getattr(chaos, "net_drop_prob", 0.0) or 0.0)
        #: Bytes/second pacing cap, or None (the transport applies it).
        self.bandwidth_bps: "int | None" = None
        for f, bps in (getattr(chaos, "net_bandwidth_caps", ()) or ()):
            if f in flow:
                self.bandwidth_bps = int(bps)
                break
        self._seen: "set[int]" = set()
        self._delayed: "list[list[Any]]" = []
        self._partitioned = False
        self._resume_at = 0
        self._partition_buf: "list[bytes]" = []

    def transmit(self, seq: int, frame: bytes) -> "list[tuple]":
        """Fault-filter one frame transmission (first-tx-only scripting;
        the caller's seq is the record seq on replication flows, a
        per-flow data-frame counter elsewhere)."""
        out: "list[tuple]" = []
        first = seq not in self._seen
        if first:
            self._seen.add(seq)
        if self._partitioned and seq >= self._resume_at:
            self._partitioned = False
            for held in self._partition_buf:
                out.append(("send", held))
            self._partition_buf.clear()
        elif first and not self._partitioned:
            for pause, resume in self._partitions:
                if seq == pause:
                    self._partitioned = True
                    self._resume_at = resume
                    self._count("nemesis_partitions")
                    break
        if first and self._delayed:
            due = [d for d in self._delayed if d[0] <= 1]
            self._delayed = [[h - 1, f] for h, f in self._delayed if h > 1]
            for _h, held in due:
                if self._partitioned:
                    self._partition_buf.append(held)
                else:
                    out.append(("send", held))
        if self._partitioned:
            self._partition_buf.append(frame)
            return out
        if first:
            if seq in self._drop:
                self._count("nemesis_dropped")
                return out
            if self._drop_prob > 0 and hash01(
                    self._seed, "net", self.flow, seq) < self._drop_prob:
                self._count("nemesis_dropped")
                return out
            if seq in self._reset:
                # The frame is CONSUMED by the reset (never sent): the
                # connection tears mid-stream and the retransmitted tail
                # carries it over the next connection.
                self._count("nemesis_resets")
                out.append(("reset",))
                return out
            hold = self._delay.get(seq)
            if hold is not None:
                self._count("nemesis_delayed")
                self._delayed.append([hold, frame])
                return out
            if seq in self._dup:
                self._count("nemesis_dup")
                out.append(("send", frame))
        out.append(("send", frame))
        return out

    def partition(self, start: int, resume: "int | None" = None) -> None:
        """Runtime-scripted partition (the bench's kill-under-lag cut):
        transmissions of seqs >= start hold until any transmission
        reaches ``resume`` (default: never)."""
        self._partitions.append(
            (int(start), (1 << 62) if resume is None else int(resume)))


class NetNemesis:
    """Per-process fault registry: builds a :class:`FlowNemesis` per
    sender flow from the ChaosConfig script and owns the receiver-side
    deafness verdict (asymmetric partitions). Thread-safe — links live
    on the IO loop while soak drivers arm deafness from control
    threads."""

    def __init__(self, chaos: Any = None, seed: int = 0):
        self.chaos = chaos
        self.seed = int(seed)
        self._deaf_patterns: "list[str]" = list(
            getattr(chaos, "net_deaf_flows", ()) or ())
        self._lock = threading.Lock()

    def flow(self, flow_id: str,
             count: "Callable[[str], None]") -> "FlowNemesis | None":
        """Sender-side pipeline for a flow, or None when no scripted or
        seeded fault touches it (the zero-cost default path)."""
        ch = self.chaos
        if ch is None or not ch.net_faults():
            return None
        fn = FlowNemesis(flow_id, ch, self.seed, count)
        if (fn._drop or fn._dup or fn._reset or fn._delay
                or fn._partitions or fn._drop_prob > 0
                or fn.bandwidth_bps is not None):
            return fn
        return None

    # -- receiver side (asymmetric partitions) --

    def rx_deaf(self, flow_id: str) -> "Callable[[], bool]":
        """The verdict callable a connection consults per inbound read:
        True while any deaf pattern matches this flow."""
        def deaf() -> bool:
            with self._lock:
                return any(p in flow_id for p in self._deaf_patterns)
        return deaf

    def deafen(self, pattern: str) -> None:
        """Arm an asymmetric partition at runtime: inbound frames on
        every flow matching ``pattern`` drop from now on (the soak arms
        this at a deterministic quiesced boundary, then proves the
        primary self-fences within the lease budget)."""
        with self._lock:
            self._deaf_patterns.append(pattern)

    def undeafen(self) -> None:
        with self._lock:
            self._deaf_patterns = list(
                getattr(self.chaos, "net_deaf_flows", ()) or ())
