"""Per-queue write-ahead pool journal + hard-crash recovery (ISSUE 15).

The graceful path (PR 5/6/11's drain → checkpoint → restore) only fires on
SIGTERM; a hard crash (OOM, host loss, ``kill -9``) previously lost the
entire waiting pool, the held admission credits, and the at-least-once
dedup/replay cache. This module makes failure a *planned transition with
bounded, measured blackout*:

- **Framing.** A journal segment is a sequence of CRC-framed records:
  ``<IIQB`` header (crc32, payload length, record seq, record type) +
  payload bytes, crc computed over (length, seq, type, payload). The first
  record is a version-stamped SEGMENT header naming the snapshot the
  segment's records follow. A torn tail (crash mid-write) parses as "stop
  here", never as garbage records.

- **Record types.** ``ADMIT`` — one record per CUT WINDOW carrying every
  dispatched player's columns (the hot columnar path pays one buffered
  append per window, not per player); ``TERMINAL`` — one player reached a
  terminal state (matched / timeout / shed-evicted), payload = the encoded
  response body + dedup expiry, exactly what the ``_recent`` replay cache
  holds; ``ADMISSION`` — the AdmissionController decision checkpoint
  (written at compaction); ``CLEAN`` — clean-shutdown marker (its absence
  at boot IS the crash detector).

- **Write-ahead discipline.** Appends are buffered; ``commit()`` writes
  the buffer in one ``os.write`` and fsyncs per the configured policy
  (``none`` | ``interval`` | ``window``). The service commits before a
  terminal response is published and before a delivery is acked, so under
  ``fsync="window"`` a response the client saw implies a durable terminal
  record — the invariant that makes recovery yield zero double matches.

- **Compaction.** The live segment periodically compacts: the current seq
  ``S`` is captured under the engine lock with the pipeline drained, the
  pool snapshots to ``<queue>.snap.<S>.npz`` (utils/checkpoint format,
  atomic tmp+rename), the live segment rotates to ``.prev`` and a fresh
  segment opens anchored at ``S``, carrying the live dedup entries and the
  admission checkpoint forward. Replay filters by SEQ, not by file, so a
  crash at any point inside compaction recovers losslessly (the
  crash-during-compaction test pins "old snapshot still wins").

- **Recovery.** ``PoolJournal`` attaches to whatever artifacts exist at
  construction: it picks the newest snapshot that *verifies* (falling back
  to the previous good one with a speakable warning on corruption),
  replays the retained segments' records with seq > snapshot seq into a
  final (waiting, removed, recent, admission) state, and reports whether
  the shutdown was clean. The app applies that state to the engine and
  measures the whole span as ``crash_rto_ms``.
"""

from __future__ import annotations

import base64
import dataclasses
import glob
import json
import logging
import os
import re
import struct
import threading
import time
import zlib
from typing import Any

log = logging.getLogger(__name__)

FORMAT_VERSION = 1

#: Record frame header: crc32, payload length, record seq, record type.
_HEADER = struct.Struct("<IIQB")

RT_SEGMENT = 0   #: segment header (version stamp + snapshot anchor)
RT_ADMIT = 1     #: one cut window's dispatched players (columns)
RT_TERMINAL = 2  #: one player's terminal (response body + dedup expiry)
RT_ADMISSION = 3  #: AdmissionController decision checkpoint
RT_CLEAN = 4     #: clean-shutdown marker
RT_TERMINALS = 5  #: one window's terminals in ONE record (the hot path:
#                  one json+crc+lock acquire per window, not per player)


class FencedError(RuntimeError):
    """Append refused: this journal's owner was epoch-fenced (ISSUE 17).
    A superseded ex-primary must not extend the WAL — the standby's
    successor owns this queue's history now. Raised by ``_append`` when
    the installed ``fence`` check fails."""

_FSYNC_POLICIES = ("none", "interval", "window")

_SNAP_RE = re.compile(r"\.snap\.(\d+)\.npz$")


def journal_path(directory: str, queue: str) -> str:
    return os.path.join(directory, f"{queue}.journal")


def snapshot_path(directory: str, queue: str, seq: int) -> str:
    return os.path.join(directory, f"{queue}.snap.{seq:012d}.npz")


def list_snapshots(directory: str, queue: str) -> list[tuple[int, str]]:
    """(seq, path) of every compaction snapshot for ``queue``, newest
    first. ``.tmp`` leftovers from an interrupted compaction never match."""
    out: list[tuple[int, str]] = []
    for path in glob.glob(os.path.join(directory, f"{queue}.snap.*.npz")):
        m = _SNAP_RE.search(path)
        if m is not None:
            out.append((int(m.group(1)), path))
    out.sort(reverse=True)
    return out


def _frame(seq: int, rtype: int, payload: bytes) -> bytes:
    crc = zlib.crc32(struct.pack("<IQB", len(payload), seq, rtype))
    crc = zlib.crc32(payload, crc)
    return _HEADER.pack(crc, len(payload), seq, rtype) + payload


def read_segment(path: str) -> tuple[dict[str, Any], list[tuple[int, int, bytes]], bool, int]:
    """Parse one segment: (header dict, [(seq, rtype, payload)], torn,
    intact byte offset).

    Stops cleanly at the first truncated/CRC-bad frame — a torn tail is
    the normal post-crash shape, not an error; everything before it is
    intact by the per-record CRC, and ``intact`` is where a re-attaching
    writer may truncate-and-append. Raises :class:`ValueError` only when
    the SEGMENT header itself is unreadable (the file is not a journal)."""
    records: list[tuple[int, int, bytes]] = []
    torn = False
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    header: dict[str, Any] | None = None
    while off + _HEADER.size <= len(data):
        crc, length, seq, rtype = _HEADER.unpack_from(data, off)
        end = off + _HEADER.size + length
        if end > len(data):
            torn = True
            break
        payload = data[off + _HEADER.size:end]
        want = zlib.crc32(struct.pack("<IQB", length, seq, rtype))
        want = zlib.crc32(payload, want)
        if want != crc:
            torn = True
            break
        if rtype == RT_SEGMENT:
            if header is None:
                header = json.loads(payload.decode("utf-8"))
            # A stray later SEGMENT record is ignored (never written).
        else:
            records.append((seq, rtype, payload))
        off = end
    if off < len(data):
        torn = True
    if header is None:
        raise ValueError(f"{path}: no valid segment header")
    if header.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"{path}: unsupported journal version {header.get('version')}")
    return header, records, torn, off


def admit_payload(rows: list[list[Any]]) -> bytes:
    """One cut window's ADMIT payload. Each row:
    [id, rating, rd, region, mode, threshold|None, enqueued_at, reply_to,
    correlation_id, tier, deadline] — region/mode by NAME (codes are
    process-local), the utils/checkpoint portability rule."""
    return json.dumps({"rows": rows}, separators=(",", ":")).encode("utf-8")


def terminal_payload(pid: str, body: bytes, expiry: float) -> bytes:
    return json.dumps(
        {"id": pid, "body": base64.b64encode(body).decode("ascii"),
         "exp": expiry}, separators=(",", ":")).encode("utf-8")


def terminals_payload(entries: "list[tuple[str, bytes, float]]") -> bytes:
    """One window's terminals as a single RT_TERMINALS payload."""
    return json.dumps(
        {"t": [[pid, base64.b64encode(body).decode("ascii"), exp]
               for pid, body, exp in entries]},
        separators=(",", ":")).encode("utf-8")


def row_to_request(row: list[Any]):
    """Inverse of the ADMIT row shape → SearchRequest (the engine.restore
    payload — same fidelity as utils/checkpoint's object fallback)."""
    from matchmaking_tpu.service.contract import SearchRequest

    thr = row[5]
    return SearchRequest(
        id=str(row[0]), rating=float(row[1]), rating_deviation=float(row[2]),
        region=str(row[3]), game_mode=str(row[4]),
        rating_threshold=None if thr is None else float(thr),
        enqueued_at=float(row[6]), reply_to=str(row[7]),
        correlation_id=str(row[8]), tier=int(row[9]),
        deadline_at=float(row[10]))


@dataclasses.dataclass
class RecoveredQueue:
    """The journal's view of one queue at boot, ready to apply."""

    queue: str
    #: Clean-shutdown marker present (no crash recovery needed).
    clean: bool = True
    #: Newest snapshot that VERIFIED, or "" (start from empty).
    snapshot: str = ""
    snapshot_seq: int = 0
    #: A newer snapshot existed but failed verification (fell back).
    fallback: bool = False
    #: Speakable corruption notes (corrupt snapshots, torn tails).
    corrupt: list[str] = dataclasses.field(default_factory=list)
    #: id → admit row for journal-admitted players still waiting.
    waiting: dict[str, list[Any]] = dataclasses.field(default_factory=dict)
    #: ids that reached a terminal state and were NOT re-admitted after
    #: (applied to the snapshot with engine.remove at recovery).
    removed: set[str] = dataclasses.field(default_factory=set)
    #: id → (response body, dedup expiry): the ``_recent`` replay cache.
    recent: dict[str, tuple[bytes, float]] = dataclasses.field(
        default_factory=dict)
    #: Last AdmissionController checkpoint seen, or None.
    admission: dict[str, Any] | None = None
    last_seq: int = 0
    replayed: int = 0

    def transcript(self) -> dict[str, Any]:
        """Deterministic content summary (the two-run bit-identity pin):
        a pure function of the recovered STATE, independent of window
        framing, record grouping, AND compaction cadence — the snapshot
        name carries its anchor seq (a framing fact), so only its
        presence is recorded."""
        return {
            "queue": self.queue,
            "clean": self.clean,
            "snapshot": bool(self.snapshot),
            "fallback": self.fallback,
            "waiting": sorted(self.waiting),
            "removed": sorted(self.removed),
            "recent": sorted(self.recent),
        }


def _verify_snapshot(path: str) -> bool:
    """Fully read a pool snapshot (np.load + meta + every array) so a
    truncated/bit-flipped file is caught HERE, before recovery commits to
    replaying against it."""
    import numpy as np

    try:
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(str(z["meta"]))
            if meta.get("version") is None:
                return False
            for name in z.files:
                z[name]  # force decompression — zip CRCs check the bytes
        return True
    except Exception:
        return False


# protocol-monotone: seq, synced_seq, last_seq
class PoolJournal:
    """One queue's write-ahead journal. Thread-safe: appends come from the
    event loop (terminal settles) AND from engine-lock-holding worker
    threads (window dispatch), so every mutation runs under an internal
    ``threading.Lock``.

    Construction ATTACHES to existing artifacts (recovery parse into
    ``self.recovered``) and continues the sequence numbering past the
    newest record — it never truncates state; compaction and the clean
    marker are explicit calls."""

    def __init__(self, directory: str, queue: str, *, fsync: str = "none",
                 fsync_interval_s: float = 0.05,
                 compact_records: int = 50_000,
                 compact_bytes: int = 8 << 20,
                 keep_snapshots: int = 2):
        if fsync not in _FSYNC_POLICIES:
            raise ValueError(
                f"unknown fsync policy {fsync!r} (one of {_FSYNC_POLICIES})")
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.queue = queue
        self.fsync = fsync
        self.fsync_interval_s = fsync_interval_s
        self.compact_records = max(1, compact_records)
        self.compact_bytes = max(1, compact_bytes)
        self.keep_snapshots = max(1, keep_snapshots)
        self._lock = threading.Lock()
        self._buf: list[bytes] = []  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        #: Bytes written out (os.write) but not yet fsynced — what a
        #: policy commit still owes durability for.
        self._unsynced = False  # guarded-by: _lock
        self._last_fsync = time.monotonic()  # guarded-by: _lock
        #: Monotone record sequence (recovery replay order; the matchlint
        #: determinism rule guards this against wall-clock arithmetic).
        self.seq = 0  # guarded-by: _lock
        #: Highest seq covered by an fsync — the durability watermark
        #: (seq - synced_seq = records a HOST loss could still drop;
        #: surfaced per queue in the /metrics durability report).
        self.synced_seq = 0  # guarded-by: _lock
        #: Live-segment accounting (compaction trigger).
        self.segment_records = 0  # guarded-by: _lock
        self.segment_bytes = 0  # guarded-by: _lock
        #: Lifetime write-amplification accounting: file bytes written vs
        #: logical payload bytes journaled (bench.py --crash-soak reports
        #: the ratio).
        self.bytes_written = 0  # guarded-by: _lock
        self.payload_bytes = 0  # guarded-by: _lock
        self._fd: int | None = None  # guarded-by: _lock
        #: Replication stream tap (ISSUE 17, service/replication.py; None
        #: = replication off): called as ``tap(seq, rtype, payload)``
        #: inside the append lock for EVERY sealed record — appends AND
        #: the compaction carries (which consume seqs without going
        #: through ``_append``; an untapped carry would stall the
        #: standby's contiguous-apply watermark forever).
        self.tap = None
        #: Epoch fence (ISSUE 17; None = unfenced): a ``() -> bool``
        #: check run at the top of every append — False means this
        #: journal's owner was superseded and ``_append`` raises
        #: :class:`FencedError` instead of extending history.
        self.fence = None
        #: Recovery parse of whatever artifacts existed at attach (None =
        #: nothing on disk: a genuinely fresh boot).
        self.recovered: RecoveredQueue | None = self._attach()
        if self.recovered is not None:
            self.seq = self.recovered.last_seq
            self._reopen_live()
        else:
            self._open_segment(snapshot="", snapshot_seq=0)
        self.synced_seq = self.seq

    # ---- attach / recovery -------------------------------------------------

    def _reopen_live(self) -> None:
        """Re-attach the writer to the existing live segment: truncate a
        torn tail back to the last intact frame (appending after garbage
        would hide every later record from replay), then append. A live
        segment that is missing or headerless gets a fresh one."""
        live = journal_path(self.directory, self.queue)
        if not os.path.exists(live) or self._live_intact < 0:
            self._open_segment(snapshot="", snapshot_seq=0)
            return
        fd = os.open(live, os.O_WRONLY)
        if self._live_intact:
            os.ftruncate(fd, self._live_intact)
        os.lseek(fd, 0, os.SEEK_END)
        self._fd = fd
        self.segment_records = 0  # conservative: rotation decides anyway
        self.segment_bytes = os.fstat(fd).st_size

    def _attach(self) -> RecoveredQueue | None:
        #: Intact byte offset of the live segment (-1 = unreadable, 0 =
        #: intact end-to-end — ftruncate(0) is never wanted, so 0 means
        #: "no truncation needed" here).
        self._live_intact = 0
        live = journal_path(self.directory, self.queue)
        prev = live + ".prev"
        snaps = list_snapshots(self.directory, self.queue)
        if not os.path.exists(live) and not os.path.exists(prev) \
                and not snaps:
            return None
        rec = RecoveredQueue(queue=self.queue)
        # Newest VERIFIED snapshot wins; a corrupt newer one falls back to
        # the previous good generation with a speakable note instead of
        # crashing the boot (the satellite-1 contract).
        first = True
        for seq, path in snaps:
            if _verify_snapshot(path):
                rec.snapshot, rec.snapshot_seq = path, seq
                rec.fallback = not first
                break
            rec.corrupt.append(
                f"snapshot {os.path.basename(path)} failed verification "
                f"(truncated or corrupt) — falling back")
            first = False
        # Replay retained segments oldest-first; seq filtering (not file
        # filtering) makes a crash at any compaction point lossless.
        records: list[tuple[int, int, bytes]] = []
        clean = False
        torn_any = False
        any_segment = False
        for path in (prev, live):
            if not os.path.exists(path):
                continue
            try:
                _header, recs, torn, intact = read_segment(path)
            except ValueError as e:
                rec.corrupt.append(str(e))
                if path == live:
                    self._live_intact = -1  # headerless: rebuild it
                continue
            any_segment = True
            if torn:
                torn_any = True
                rec.corrupt.append(
                    f"{os.path.basename(path)}: torn tail — replay stops "
                    f"at the last intact record")
                if path == live:
                    self._live_intact = intact
            records.extend(recs)
        records.sort(key=lambda r: r[0])
        for seq, rtype, payload in records:
            rec.last_seq = max(rec.last_seq, seq)
            if rtype == RT_CLEAN:
                clean = True
                continue
            clean = False  # any later mutation reopens the journal
            if rtype == RT_ADMIT:
                if seq <= rec.snapshot_seq:
                    continue  # pool membership superseded by the snapshot
                rec.replayed += 1
                for row in json.loads(payload.decode("utf-8"))["rows"]:
                    rec.waiting[str(row[0])] = row
                    rec.removed.discard(str(row[0]))
            elif rtype in (RT_TERMINAL, RT_TERMINALS):
                # Terminals rebuild ``recent`` REGARDLESS of seq: the
                # at-least-once dedup horizon is not pool state, so a
                # pre-anchor terminal surviving in the .prev segment still
                # counts (this is what makes a crash between compaction's
                # two renames lossless — the carries may be gone, but the
                # old segment's terminals are not). Pool effects (waiting/
                # removed) stay seq-filtered: the snapshot is the pool
                # truth at the anchor.
                d = json.loads(payload.decode("utf-8"))
                entries = (d["t"] if rtype == RT_TERMINALS
                           else [[d["id"], d["body"], d["exp"]]])
                for pid, b64, exp in entries:
                    pid = str(pid)
                    rec.recent[pid] = (base64.b64decode(b64), float(exp))
                    if seq > rec.snapshot_seq:
                        rec.replayed += 1
                        rec.waiting.pop(pid, None)
                        rec.removed.add(pid)
            elif rtype == RT_ADMISSION:
                # Checkpoint, not a delta: the newest retained one wins
                # whatever its seq (records replay in seq order).
                rec.admission = json.loads(payload.decode("utf-8"))
        # No segment at all (snapshot-only dir): treat as unclean — the
        # process died between snapshot and segment creation. A torn tail
        # also voids the marker: something wrote after it.
        rec.clean = clean and not torn_any if any_segment else False
        return rec

    # ---- the append/commit hot path ----------------------------------------

    def _open_segment(self, snapshot: str, snapshot_seq: int) -> None:
        header = {"version": FORMAT_VERSION, "queue": self.queue,
                  "snapshot": os.path.basename(snapshot) if snapshot else "",
                  "snapshot_seq": snapshot_seq}
        frame = _frame(0, RT_SEGMENT,
                       json.dumps(header, separators=(",", ":")).encode())
        path = journal_path(self.directory, self.queue)
        fd = os.open(path + ".new", os.O_CREAT | os.O_TRUNC | os.O_WRONLY,
                     0o644)
        os.write(fd, frame)
        os.fsync(fd)
        os.replace(path + ".new", path)
        self._fd = fd
        self.segment_records = 0
        self.segment_bytes = len(frame)
        self.bytes_written += len(frame)

    # protocol-effect: journal_append requires-fence fence
    def _append(self, rtype: int, payload: bytes, logical: int,
                writeout: bool = False) -> int:
        """THE append seam (the sanitizer's journal twin patches exactly
        this): assign the next seq, frame, and buffer — or, with
        ``writeout``, ``os.write`` the frame directly inside the same
        lock hold (the hot-path records: the buffer is then never
        observably dirty, so a concurrent settle's acked-after-append
        audit cannot race a half-staged append; a PROCESS crash cannot
        lose written bytes, so this is also what recovers a mid-window
        crash's players as waiting). Returns the seq."""
        if self.fence is not None and not self.fence():
            # Epoch fencing (ISSUE 17): a superseded ex-primary CANNOT
            # extend the WAL — checked before the lock so a fenced
            # writer never even contends with the successor's history.
            raise FencedError(
                f"journal append for {self.queue!r} refused: owner was "
                f"epoch-fenced (a standby took over this queue)")
        with self._lock:
            if self._closed:
                raise RuntimeError(
                    f"journal for {self.queue!r} is closed (append after "
                    f"clean-shutdown marker)")
            self.seq += 1
            seq = self.seq
            frame = _frame(seq, rtype, payload)
            if writeout and self._fd is not None:
                os.write(self._fd, frame)
                self.segment_records += 1
                self.segment_bytes += len(frame)
                self.bytes_written += len(frame)
                self._unsynced = True
            else:
                self._buf.append(frame)
            self.payload_bytes += logical
            if self.tap is not None:
                # Replication stream (ISSUE 17): ship the sealed record.
                # Never let a tap failure poison the append — replication
                # loss is bounded by acks; a failed append is data loss.
                try:
                    self.tap(seq, rtype, payload)
                except Exception:
                    log.exception("journal tap failed for %r seq %d",
                                  self.queue, seq)
            return seq

    def append_admits(self, rows: list[list[Any]]) -> int:
        """One cut window's dispatched players — ONE record, written out
        in the append (host-loss durability is only promised at the
        response/ack commit points, where the policy fsync runs)."""
        payload = admit_payload(rows)
        return self._append(RT_ADMIT, payload, len(payload), writeout=True)

    def append_terminal(self, pid: str, body: bytes, expiry: float) -> int:
        return self._append(RT_TERMINAL, terminal_payload(pid, body, expiry),
                            len(body))

    def append_terminals(self,
                         entries: "list[tuple[str, bytes, float]]") -> int:
        """One cut window's terminals — ONE record (one json+crc+lock
        acquire per window), written out in the append like the admits."""
        return self._append(RT_TERMINALS, terminals_payload(entries),
                            sum(len(b) for _, b, _ in entries),
                            writeout=True)

    @property
    def dirty(self) -> bool:
        return bool(self._buf)

    # holds-lock: _lock
    def _writeout_locked(self) -> None:
        """Drain the frame buffer in one os.write (caller holds _lock)."""
        if not self._buf or self._fd is None:
            return
        data = b"".join(self._buf)
        n = len(self._buf)
        self._buf.clear()
        os.write(self._fd, data)
        self.segment_records += n
        self.segment_bytes += len(data)
        self.bytes_written += len(data)
        self._unsynced = True

    def flush_buffer(self) -> None:
        """Write the buffered frames WITHOUT any fsync, whatever the
        policy — the admit-at-dispatch point. A PROCESS crash cannot lose
        os.write'd bytes (the page cache outlives the process), so a
        mid-window crash still recovers the window's players as waiting;
        host-loss durability is only promised at the response/ack commit
        points, where ``commit()`` runs the policy fsync. Keeping the
        dispatch path fsync-free is what holds the fsync="window" steady-
        state overhead to ONE fsync per window."""
        with self._lock:
            self._writeout_locked()

    @property
    def needs_commit(self) -> bool:
        """Anything for the service's write-ahead commit point to do:
        buffered frames, or written-but-unsynced bytes a durability
        policy still owes an fsync."""
        if self._buf:
            return True
        return self._unsynced and self.fsync in ("interval", "window")

    def commit(self, force_sync: bool = False) -> None:
        """Write the buffered frames in one os.write; fsync per policy
        (covering any earlier ``flush_buffer`` writeouts too). Called by
        the service before a terminal response publishes and before a
        delivery acks — the write-ahead points."""
        with self._lock:
            self._writeout_locked()
            if self._fd is None or not (force_sync or self._unsynced):
                return
            if force_sync or self.fsync == "window":
                written = self.seq
                os.fsync(self._fd)
                self._unsynced = False
                self.synced_seq = max(self.synced_seq, written)
                self._last_fsync = time.monotonic()
            elif self.fsync == "interval":
                now = time.monotonic()
                if now - self._last_fsync >= self.fsync_interval_s:
                    written = self.seq
                    os.fsync(self._fd)
                    self._unsynced = False
                    self.synced_seq = max(self.synced_seq, written)
                    self._last_fsync = now

    def wants_compact(self) -> bool:
        return (self.segment_records >= self.compact_records
                or self.segment_bytes >= self.compact_bytes)

    # ---- compaction --------------------------------------------------------

    def compact_begin(self) -> tuple[int, str]:
        """Capture the compaction anchor. Caller MUST hold the queue's
        engine lock with the pipeline drained (so the pool cannot mutate
        between the seq capture and the snapshot write) and then write the
        pool snapshot to the returned path (utils/checkpoint.save_pool —
        atomic by construction). Returns (anchor seq, snapshot path)."""
        self.commit()
        with self._lock:
            return self.seq, snapshot_path(self.directory, self.queue,
                                           self.seq)

    # protocol-effect: journal_append requires-fence fence
    def compact_finish(self, anchor_seq: int, snap_path: str,
                       carry_terminals: list[tuple[str, bytes, float]] = (),
                       admission: dict[str, Any] | None = None) -> None:
        """Rotate to a fresh segment anchored at the (verified) snapshot,
        carrying the live dedup entries + admission checkpoint forward so
        the at-least-once horizon survives the truncation.

        Crash-atomic by construction: the successor segment is built
        COMPLETE (header + carries + admission, fsynced) in a side file
        before the two renames, so at every crash point recovery reads a
        consistent (snapshot, segments) pair — and the seq-unfiltered
        TERMINAL replay in ``_attach`` covers the one window between the
        renames where the carries are not yet the live segment (the old
        segment's terminals still are)."""
        if self.fence is not None and not self.fence():
            # A fenced ex-primary must not rewrite history either —
            # compaction rotates segments and consumes seqs.
            raise FencedError(
                f"journal compaction for {self.queue!r} refused: owner "
                f"was epoch-fenced")
        if not _verify_snapshot(snap_path):
            # Never truncate history against a snapshot that does not
            # read back: the old segment keeps covering the pool.
            raise ValueError(
                f"compaction snapshot {snap_path!r} failed verification — "
                f"keeping the current journal segment")
        live = journal_path(self.directory, self.queue)
        with self._lock:
            header = {"version": FORMAT_VERSION, "queue": self.queue,
                      "snapshot": os.path.basename(snap_path),
                      "snapshot_seq": anchor_seq}
            frames = [_frame(0, RT_SEGMENT,
                             json.dumps(header,
                                        separators=(",", ":")).encode())]
            logical = 0
            #: Compaction carries consume seqs without going through
            #: ``_append`` — tap them too (ISSUE 17), or the replication
            #: standby would stall forever waiting for the gap. Carries
            #: are re-statements of already-streamed state, so the
            #: standby's apply is idempotent over them.
            tapped: list[tuple[int, int, bytes]] = []
            for pid, body, exp in carry_terminals:
                self.seq += 1
                payload = terminal_payload(pid, body, exp)
                frames.append(_frame(self.seq, RT_TERMINAL, payload))
                tapped.append((self.seq, RT_TERMINAL, payload))
                logical += len(body)
            if admission is not None:
                self.seq += 1
                payload = json.dumps(admission,
                                     separators=(",", ":")).encode("utf-8")
                frames.append(_frame(self.seq, RT_ADMISSION, payload))
                tapped.append((self.seq, RT_ADMISSION, payload))
                logical += len(payload)
            if self.tap is not None:
                for seq, rtype, payload in tapped:
                    try:
                        self.tap(seq, rtype, payload)
                    except Exception:
                        log.exception("journal tap failed for %r seq %d",
                                      self.queue, seq)
            data = b"".join(frames)
            fd = os.open(live + ".new",
                         os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o644)
            os.write(fd, data)
            os.fsync(fd)
            if self._fd is not None:
                os.fsync(self._fd)
                os.close(self._fd)
                self._fd = None
            if os.path.exists(live):
                os.replace(live, live + ".prev")
            os.replace(live + ".new", live)
            self._fd = fd
            self.segment_records = len(frames) - 1
            self.segment_bytes = len(data)
            self.bytes_written += len(data)
            self.payload_bytes += logical
            # The successor was fsynced before the renames and the old
            # segment before close, so everything appended so far is
            # durable — keep the watermark true.
            self._unsynced = False
            self.synced_seq = max(self.synced_seq, self.seq)
        self._gc(anchor_seq)

    def _gc(self, anchor_seq: int) -> None:
        """Drop snapshot generations beyond the retention window (the
        anchor counts as generation 1)."""
        snaps = list_snapshots(self.directory, self.queue)
        keep = {path for seq, path in snaps[:self.keep_snapshots]}
        for _seq, path in snaps:
            if path not in keep:
                try:
                    os.unlink(path)
                except OSError:
                    log.warning("could not gc old snapshot %s", path)

    # ---- lifecycle ---------------------------------------------------------

    def mark_clean(self) -> None:
        """Append the clean-shutdown marker and make it durable — boot
        sees this and skips crash recovery."""
        self._append(RT_CLEAN, b"", 0)
        self.commit(force_sync=True)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fd is not None:
                if self._buf:
                    data = b"".join(self._buf)
                    self._buf.clear()
                    os.write(self._fd, data)
                    self.bytes_written += len(data)
                os.fsync(self._fd)
                os.close(self._fd)
                self._fd = None

    def abandon(self) -> None:
        """Crash-fidelity teardown (bench --crash-soak / tests): DROP the
        uncommitted buffer (a real crash loses it) and close the fd
        without a clean marker or fsync — the on-disk state is exactly
        what a ``kill -9`` would leave."""
        with self._lock:
            self._closed = True
            self._buf.clear()
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
