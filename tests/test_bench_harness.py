"""bench.py harness robustness (round-4 verdict ask #2 + ISSUE 6 satellite).

Round 2 lost ALL perf evidence to a single transient backend-init failure
(`BENCH_r02.json` rc=1 at `jax.devices()`); round 5 lost a whole round to a
hung TPU init probe even though the harness survived (one
``backend_unavailable`` line, no data). The harness must retry bounded and,
on persistent failure:

- with ``--no-cpu-fallback``: still print ONE parseable JSON line with
  ``"error": "backend_unavailable"`` and exit 0 (the legacy diagnostic);
- by default: fall back to the CPU-mesh e2e config and print ONE JSON line
  tagged ``"backend": "cpu-fallback"`` with a real (degraded) trajectory
  point instead of aborting.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _broken_backend_env() -> dict:
    env = dict(os.environ)
    # Force backend init to fail fast and deterministically: an unknown
    # platform makes jax.devices() raise in both the probe subprocess and
    # (hypothetically) in-process. PALLAS_AXON_POOL_IPS must go too —
    # with it set, the machine's sitecustomize dials the TPU relay at
    # INTERPRETER START of every subprocess, which hangs when the shared
    # backend is down (observed in round 4) and would hang these tests.
    env["JAX_PLATFORMS"] = "definitely_not_a_backend"
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("MM_BENCH_CPU_FALLBACK", None)
    return env


def test_backend_unavailable_prints_diagnostic_json_line():
    """Legacy diagnostic path (--no-cpu-fallback): bounded retry, one
    parseable error line, rc 0."""
    proc = subprocess.run(
        [sys.executable, BENCH, "--init-retries", "2", "--init-delay", "0",
         "--no-cpu-fallback"],
        capture_output=True, text=True, timeout=300,
        env=_broken_backend_env(), cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines!r}"
    payload = json.loads(lines[0])
    assert payload["error"] == "backend_unavailable"
    assert payload["value"] is None
    assert payload["unit"] == "matches/sec"
    # Retry really was bounded: stderr shows the retry log line.
    assert "retry 1/1" in proc.stderr


def test_backend_unavailable_falls_back_to_cpu_mesh():
    """ISSUE 6 satellite (ROADMAP carry-over from BENCH_r05): when the TPU
    init probe fails past its budget, the DEFAULT behavior runs the
    CPU-mesh e2e config and records a partial trajectory point tagged
    ``backend: cpu-fallback`` — with SLO attainment and idle-fraction
    fields — instead of aborting."""
    proc = subprocess.run(
        [sys.executable, BENCH, "--init-retries", "1", "--init-delay", "0",
         # keep the fallback point small enough for a CI box: tiny pool,
         # short phase, no sweep/comms/multiproc
         "--pool", "400", "--capacity", "1024", "--pool-block", "256",
         "--window", "64", "--depth", "2",
         "--e2e-rate", "200", "--e2e-seconds", "1",
         "--e2e-rates", "", "--skip-multiproc",
         "--fallback-skip-comms"],
        capture_output=True, text=True, timeout=540,
        env=_broken_backend_env(), cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines!r}"
    payload = json.loads(lines[0])
    assert payload["backend"] == "cpu-fallback"
    assert payload["tpu_error"] == "backend_unavailable"
    assert "error" not in payload  # the fallback point is real data
    # a real (degraded) trajectory point: the e2e phase ran
    assert payload["value"] is not None
    assert payload["e2e_requests"] > 0
    assert payload["e2e_players_matched"] > 0
    # ISSUE 6: the BENCH json embeds SLO attainment + idle fraction
    assert "e2e_slo_attainment" in payload
    assert 0.0 <= payload["e2e_idle_fraction"] <= 1.0
    assert payload["telemetry"], "telemetry trajectory missing"
    assert "metrics_report" in payload


def test_init_backend_happy_path_unchanged():
    """On a working backend (CPU here), init_backend returns devices on the
    first attempt with no retries."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # see above: no relay dial in tests
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import bench\n"
        "devs = bench.init_backend(attempts=1, delay_s=0)\n"
        "assert devs, devs\n"
        "print('OK', len(devs))\n" % REPO
    )
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=300, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.startswith("OK")


def test_bench_diff_gates_e2e_rate_and_p99():
    """ISSUE 9 satellite: ``e2e_rate_req_s`` and ``e2e_p99_ms`` are
    first-class direction-aware headline gates — a 20% rate drop or p99
    rise regresses; improvements never do."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import bench_diff as bd
    finally:
        sys.path.pop(0)
    base = {"e2e_rate_req_s": 10000.0, "e2e_p99_ms": 100.0}
    worse = {"e2e_rate_req_s": 8000.0, "e2e_p99_ms": 130.0}
    rows = bd.diff(base, worse, threshold=0.10)
    flags = {r["metric"]: r["regressed"] for r in rows}
    assert flags == {"e2e_rate_req_s": True, "e2e_p99_ms": True}
    better = {"e2e_rate_req_s": 13000.0, "e2e_p99_ms": 60.0}
    rows = bd.diff(base, better, threshold=0.10)
    assert not any(r["regressed"] for r in rows)
    # Direction-awareness: a HIGHER rate with a higher p99 regresses only
    # on the p99 axis.
    mixed = {"e2e_rate_req_s": 13000.0, "e2e_p99_ms": 130.0}
    flags = {r["metric"]: r["regressed"]
             for r in bd.diff(base, mixed, threshold=0.10)}
    assert flags == {"e2e_rate_req_s": False, "e2e_p99_ms": True}


def test_bench_diff_gates_placement_blackout_and_accounting():
    """ISSUE 11 satellite: the placement-soak rows gate direction-aware
    (lower is better). lost/dup have a zero baseline on a healthy run, so
    any meaningful nonzero fresh value regresses via the base==0 rule."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import bench_diff as bd
    finally:
        sys.path.pop(0)
    base = {"placement_blackout_ms_max": 200.0,
            "placement_blackout_ms_mean": 80.0,
            "placement_lost": 0, "placement_dup": 0}
    worse = {"placement_blackout_ms_max": 400.0,
             "placement_blackout_ms_mean": 90.0,
             "placement_lost": 3, "placement_dup": 1}
    flags = {r["metric"]: r["regressed"]
             for r in bd.diff(base, worse, threshold=0.10)}
    assert flags == {"placement_blackout_ms_max": True,
                     "placement_blackout_ms_mean": True,
                     "placement_lost": True,
                     "placement_dup": True}
    better = {"placement_blackout_ms_max": 150.0,
              "placement_blackout_ms_mean": 60.0,
              "placement_lost": 0, "placement_dup": 0}
    assert not any(r["regressed"]
                   for r in bd.diff(base, better, threshold=0.10))
