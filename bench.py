#!/usr/bin/env python
"""Headline benchmark: matches/sec + p99 match latency @ 100k-player pool.

BASELINE.json: the reference (Elixir GenServer pool, sequential ETS scan per
request) caps out around ~2k concurrently-queued players; the north star is
>=100k concurrent players matched at p99 < 50 ms on TPU. This harness:

1. TPU engine: pre-fills the device pool to POOL players (restore path — no
   matching), then streams windows of fresh requests through the full engine
   step (admit scatter -> blockwise score+mask -> streaming top-k -> greedy
   conflict-free pairing -> evict scatter -> D2H), refilling the pool between
   timed windows so every measurement sees a ~POOL-player pool.
2. CPU oracle (reference semantics) at its own viable operating point
   (~2k pool) for the vs_baseline ratio — the reference publishes no numbers
   (BASELINE.json published: {}), so the oracle stands in for it.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": <matches/sec>, "unit": ..., "vs_baseline": ...}
plus supporting fields (p99_ms, pool, cpu_mps, ...). Diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def init_backend(attempts: int = 5, delay_s: float = 60.0):
    """Initialize the JAX backend with bounded retry.

    The shared axon TPU tunnel has transient outages (round 2 lost ALL bench
    evidence to a single init failure; this session observed both hard errors
    and multi-minute init hangs). Each attempt first probes in a subprocess
    with a timeout, then — on a green probe — initializes in-process inside a
    daemon thread with its own timeout, so the probe-passed-then-backend-died
    race cannot hang the harness unbounded either (the wedged thread leaks,
    but daemon threads don't block process exit and the retry loop moves on).

    Returns the device list, or None after ``attempts`` failures (caller must
    print the diagnostic JSON line and exit 0 so the driver records the
    outage instead of a crash)."""
    import subprocess
    import threading

    def init_inprocess(timeout_s: float = 120.0):
        box: dict = {}

        def run():
            try:
                import jax

                box["devices"] = jax.devices()
            except Exception as e:
                box["error"] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            # The wedged thread holds jax's global backend-init lock, so NO
            # later in-process attempt in this process can ever succeed —
            # they would all block on that lock and time out even after the
            # backend recovers. Re-exec the whole harness with a bounded
            # budget: exec replaces the process image (wedged thread dies),
            # giving the next attempt a clean jax.
            reexecs = int(os.environ.get("MM_BENCH_REEXEC", "0"))
            if reexecs < 3:
                log(f"[init] in-process init hung; re-exec "
                    f"({reexecs + 1}/3) for a clean jax state")
                os.environ["MM_BENCH_REEXEC"] = str(reexecs + 1)
                sys.stderr.flush()
                sys.stdout.flush()
                os.execv(sys.executable, [sys.executable] + sys.argv)
            log("[init] in-process init hung past its timeout "
                "(re-exec budget spent)")
            return None
        if "error" in box:
            log(f"[init] in-process init failed after green probe: "
                f"{box['error']!r}")
            return None
        return box.get("devices") or None

    for attempt in range(attempts):
        if attempt > 0:
            log(f"[init] backend unavailable; retry {attempt}/{attempts - 1} "
                f"in {delay_s:.0f}s")
            time.sleep(delay_s)
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; assert len(jax.devices()) > 0"],
                timeout=120, capture_output=True)
            if probe.returncode != 0:
                tail = probe.stderr.decode(errors="replace").strip().splitlines()
                log(f"[init] probe failed: {tail[-1] if tail else 'no stderr'}")
                continue
            devices = init_inprocess()
            if devices:
                return devices
        except subprocess.TimeoutExpired:
            log("[init] probe timed out after 120s (backend hang)")
    return None


def make_requests(rng: np.random.Generator, n: int, start_id: int,
                  now: float, threshold: float | None = None):
    from matchmaking_tpu.service.contract import SearchRequest

    ratings = rng.normal(1500.0, 300.0, size=n)
    return [
        SearchRequest(
            id=f"p{start_id + i}",
            rating=float(ratings[i]),
            rating_threshold=threshold,
            enqueued_at=now,
        )
        for i in range(n)
    ]


def make_columns(rng: np.random.Generator, n: int, start_id: int, now: float):
    """Columnar window (the fast path the service batcher also produces)."""
    from matchmaking_tpu.service.contract import RequestColumns

    return RequestColumns(
        ids=np.char.add("p", np.arange(start_id, start_id + n).astype(str)).astype(object),
        rating=rng.normal(1500.0, 300.0, size=n).astype(np.float32),
        rd=np.zeros(n, np.float32),
        region=np.zeros(n, np.int32),
        mode=np.zeros(n, np.int32),
        threshold=np.full(n, np.nan, np.float32),
        enqueued_at=np.full(n, now, np.float64),
    )


def run_engine(engine, rng: np.random.Generator, *, pool_target: int,
               window: int, warmup: int, measured: int, label: str):
    """Stream windows through ``engine.search`` at a sustained pool size.

    Returns (matches_per_sec, per-window latencies in seconds, total matches).
    """
    next_id = 0
    now = 0.0

    def refill() -> None:
        nonlocal next_id, now
        deficit = pool_target - engine.pool_size()
        while deficit > 0:
            chunk = min(deficit, 4096)
            fillers = make_requests(rng, chunk, next_id, now)
            next_id += chunk
            engine.restore(fillers, now)
            deficit -= chunk

    refill()
    log(f"[{label}] pool filled to {engine.pool_size()}")

    latencies: list[float] = []
    total_matches = 0
    measured_time = 0.0
    for i in range(warmup + measured):
        reqs = make_requests(rng, window, next_id, now)
        next_id += window
        t0 = time.perf_counter()
        out = engine.search(reqs, now)
        dt = time.perf_counter() - t0
        now += max(dt, 1e-4)
        if i >= warmup:
            latencies.append(dt)
            total_matches += len(out.matches)
            measured_time += dt
        refill()

    mps = total_matches / measured_time if measured_time > 0 else 0.0
    return mps, latencies, total_matches


def run_engine_pipelined(engine, rng: np.random.Generator, *, pool_target: int,
                         window: int, warmup: int, measured: int, depth: int,
                         label: str, gen=None):
    """Stream windows through the pipelined API (``search_async`` +
    ``collect_ready``) keeping ≤ ``depth`` windows in flight.

    Latency per window = dispatch call → results collected on host (the
    end-to-end path a request sees past the batcher). Throughput is counted
    over the measured tokens' span.
    """
    gen = gen or make_columns
    next_id = 0
    wall0 = time.perf_counter()

    def wall() -> float:
        return time.perf_counter() - wall0

    def refill() -> None:
        nonlocal next_id
        deficit = pool_target - engine.pool_size()
        while deficit > 0:
            chunk = min(deficit, 8192)
            engine.restore_columns(gen(rng, chunk, next_id, wall()), wall())
            next_id += chunk
            deficit -= chunk

    refill()
    log(f"[{label}] pool filled to {engine.pool_size()}")

    submit_t: dict[int, float] = {}
    timed: dict[int, bool] = {}
    latencies: list[float] = []
    total_matches = 0
    t_start = None
    t_last = None

    def handle(token: int, out) -> None:
        nonlocal total_matches, t_last
        lat = time.perf_counter() - submit_t.pop(token)
        if timed.pop(token):
            latencies.append(lat)
            total_matches += out.n_matches
            t_last = time.perf_counter()

    for i in range(warmup + measured):
        cols = gen(rng, window, next_id, wall())
        next_id += window
        if i == warmup:
            t_start = time.perf_counter()
        tok = engine.search_columns_async(cols, wall())
        submit_t[tok] = time.perf_counter()
        timed[tok] = i >= warmup
        for tok2, out in engine.collect_ready():
            handle(tok2, out)
        while engine.inflight() >= depth:
            got = engine.collect_ready()
            if not got:
                time.sleep(0.0005)
            for tok2, out in got:
                handle(tok2, out)
        refill()
    for tok2, out in engine.flush():
        handle(tok2, out)

    span = (t_last - t_start) if (t_start and t_last and t_last > t_start) else 0.0
    mps = total_matches / span if span > 0 else 0.0
    return mps, latencies, total_matches


def roofline(engine, rng: np.random.Generator, *, window: int,
             iters: int = 30) -> dict:
    """Pure device-step cost + achieved-bandwidth roofline (no per-step D2H:
    steps chain on the donated pool, one sync at the end — isolates device
    time from the tunnel's ~70 ms serialized readback latency).

    The blockwise score scan reads every pool column once per window, so
    pool-bytes/step is the HBM traffic floor; utilization is reported against
    the TPU v5e's ~819 GB/s peak. Low utilization at a small window means the
    step is latency/compute-bound, not bandwidth-bound — both numbers plus
    pair-scores/s are recorded so regressions are attributable."""
    import jax
    import jax.numpy as jnp

    from matchmaking_tpu.core.pool import pack_batch

    cols = make_columns(rng, window, 10_000_000, 0.0)
    slots = engine.pool.allocate_columns(cols)
    batch = engine.pool.batch_arrays_cols(cols, slots, window, 0.0)
    packed = jnp.asarray(pack_batch(batch, 0.0))
    pool_dev = engine._dev_pool
    pool_bytes = sum(x.nbytes for x in jax.tree.leaves(pool_dev))
    step_bytes = pool_bytes + packed.nbytes
    k = engine.kernels
    # Same compiled variant the engine's hot path would pick for this
    # window (the all-ANY no-filter variant for the bench's requests).
    step = engine._step_fn(batch)
    pool_dev, out = step(pool_dev, packed)  # warm/compile
    out.block_until_ready()
    t0 = time.perf_counter()
    outs = []
    for _ in range(iters):
        pool_dev, out = step(pool_dev, packed)
        outs.append(out)
    outs[-1].block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    # The chained steps MATCH (retiring resident device-pool players the
    # host mirror still holds) and the donated pool buffers were consumed,
    # so the engine's mirror and device state have diverged: roofline must
    # be the engine's LAST use (bench_tpu calls it after the measured reps
    # and then discards the engine). Write the pool back + release the
    # scratch slots only so teardown paths stay functional.
    engine.pool.release(slots)
    engine._dev_pool = pool_dev
    peak = 819e9  # TPU v5e HBM bandwidth
    return {
        "device_step_ms": round(dt * 1e3, 3),
        "hbm_bytes_per_step": step_bytes,
        "hbm_bytes_per_s": round(step_bytes / dt, 1),
        "hbm_util_vs_819GBps": round(step_bytes / dt / peak, 4),
        "pair_scores_per_s": round(window * k.capacity / dt, 1),
    }


def _bucketed_geometry(capacity: int, pool_block: int,
                       window: int) -> dict:
    """EngineConfig extras for hierarchical bucketed formation (ISSUE 14):
    band-per-block allocation + a span budget of ~33% of the blocks.

    Sizing math (N(1500, 300) population, 100-ELO default threshold,
    equal-mass bands): a central player's admissible candidates are
    ~2·thr·φ(0)/σ ≈ 26.7% of the population mass — the irreducible
    candidate-bucket fraction — plus the sorted chunk's own mass
    (c/window of the window) and the f32 inflation. Chunks of
    ~window/64 keep the chunk-mass term under ~2% of the blocks, so a
    33% span budget leaves headroom over the ~29% requirement and every
    feasible window reports formation_touched_frac ≈ 1/3 ≪ 1 (the
    dense-fallback cond covers distribution drift)."""
    n_blocks = max(1, capacity // pool_block)
    return dict(
        bucketed=True,
        band_spec="gaussian:1500:300",
        prune_window_blocks=max(2, -(-n_blocks * 33 // 100)),
        prune_chunk=max(8, min(128, window // 64)),
    )


def bench_tpu(args) -> dict:
    from matchmaking_tpu.config import Config, EngineConfig, QueueConfig
    from matchmaking_tpu.engine.interface import make_engine

    cfg = Config(
        queues=(QueueConfig(rating_threshold=100.0),),
        engine=EngineConfig(
            backend="tpu",
            pool_capacity=args.capacity,
            pool_block=args.pool_block,
            batch_buckets=(16, 64, 256, args.window),
            top_k=8,
            readback_group=args.readback_group,
            **(_bucketed_geometry(args.capacity, args.pool_block,
                                  args.window)
               if args.bucketed else {}),
        ),
    )
    engine = make_engine(cfg, cfg.queues[0])
    rng = np.random.default_rng(0)
    # The shared TPU backend shows large multi-tenant timing variance
    # (identical compiled steps measured 10-30x apart minutes apart), so the
    # measured phase runs ``repeats`` times and the MEDIAN run is reported;
    # all samples are logged for transparency.
    profiler_cm = None
    if args.profile_dir:
        import jax

        profiler_cm = jax.profiler.trace(args.profile_dir)
        profiler_cm.__enter__()
        log(f"[tpu] jax.profiler trace → {args.profile_dir}")

    from matchmaking_tpu.utils.metrics import CompileCounter

    runs = []
    compiles_after_warmup: int | None = None
    t0 = time.perf_counter()
    try:
        for rep in range(max(1, args.repeats)):
            if rep == 1:
                # Every bucket shape compiled during rep 0; any further
                # compile is a hot-path recompile (the p99 cliff SURVEY §5's
                # recompile counter exists to expose).
                compiles_after_warmup = CompileCounter.count()
            mps, lats, total = run_engine_pipelined(
                engine, rng, pool_target=args.pool, window=args.window,
                warmup=args.warmup, measured=args.windows, depth=args.depth,
                label=f"tpu rep{rep}")
            lat_ms = np.sort(np.asarray(lats)) * 1e3
            runs.append({
                "matches_per_sec": mps,
                "p50_ms": float(np.percentile(lat_ms, 50)),
                "p99_ms": float(np.percentile(lat_ms, 99)),
                "total_matches": total,
            })
            log(f"[tpu rep{rep}] {total} matches, {mps:.0f}/s, "
                f"p99 {runs[-1]['p99_ms']:.0f} ms")
    finally:
        # The failing run is exactly the one whose profile matters.
        if profiler_cm is not None:
            profiler_cm.__exit__(None, None, None)
    log(f"[tpu] {time.perf_counter() - t0:.1f}s total incl. fill/compile")
    if hasattr(engine, "span_report"):
        log(f"[tpu] spans: {engine.span_report()}")
    recompiles = (CompileCounter.count() - compiles_after_warmup
                  if compiles_after_warmup is not None else None)
    log(f"[tpu] xla compiles total={CompileCounter.count()} "
        f"hot-path recompiles={recompiles}")
    roof = {}
    if not args.skip_roofline:
        try:
            roof = roofline(engine, rng, window=args.window)
            log(f"[tpu] roofline: {roof}")
        except Exception as e:  # pragma: no cover - perf metadata only
            log(f"[tpu] roofline failed: {e!r}")
    runs.sort(key=lambda r: r["matches_per_sec"])
    median = runs[len(runs) // 2]
    formation = (engine.formation_report()
                 if hasattr(engine, "formation_report") else None)
    return {
        **median,
        "pool": args.pool,
        "window": args.window,
        "all_runs_mps": [round(r["matches_per_sec"], 1) for r in runs],
        "hot_path_recompiles": recompiles,
        "spans": (engine.span_report()
                  if hasattr(engine, "span_report") else {}),
        **({"formation_touched_frac":
            formation.get("formation_touched_frac")}
           if formation else {}),
        **roof,
    }


def bench_e2e(args) -> dict:
    """Service-level end-to-end latency (the BASELINE metric IS end-to-end:
    a player experiences broker→middleware→batcher→engine→reply). Poisson
    arrivals are published through the in-process broker with an
    ``x-first-received`` stamp; each matched reply carries ``latency_ms`` =
    reply-publish time minus that stamp — exactly the wire-visible match
    latency. The pool is pre-filled to the target via the restore path.

    Caveats recorded with the numbers: this host has ONE core, so the
    service's Python ingress shares it with engine host work — the
    sustainable arrival rate is host-bound, not device-bound."""
    import asyncio

    async def run() -> dict:
        from matchmaking_tpu.config import (
            BatcherConfig,
            BrokerConfig,
            Config,
            EngineConfig,
            ObservabilityConfig,
            OverloadConfig,
            QueueConfig,
        )
        from matchmaking_tpu.service.app import MatchmakingApp
        from matchmaking_tpu.service.broker import Properties
        from matchmaking_tpu.service.loadgen import parse_tier_mix
        from matchmaking_tpu.service.overload import stamp_tier

        tier_mix = parse_tier_mix(getattr(args, "e2e_tier_mix", ""))
        cfg = Config(
            queues=(QueueConfig(rating_threshold=100.0,
                                send_queued_ack=False),),
            engine=EngineConfig(
                backend="tpu", pool_capacity=args.capacity,
                pool_block=args.pool_block,
                batch_buckets=(16, 64, 256, args.window), top_k=8,
                pipeline_depth=args.depth,
                readback_group=args.readback_group,
                warm_start=True),
            batcher=BatcherConfig(max_batch=args.window, max_wait_ms=3.0),
            broker=BrokerConfig(prefetch=max(8 * args.window, 4096)),
            # Overload mode (ISSUE 5): bound the waiting pool so the
            # saturation sweep measures ADMITTED-request latency under an
            # honest shed policy instead of unbounded queueing collapse.
            # Tiered mode (ISSUE 7, --e2e-tier-mix): priority classes +
            # EDF window cutting + lowest-tier-first eviction, so the
            # sweep rows show PER-TIER p99/shed under overload.
            overload=(OverloadConfig(
                max_waiting=args.e2e_max_waiting,
                # max+1, not len: a sparse mix ("0:0.5,3:0.5") must
                # configure enough tiers that x-tier 3 isn't clamped into
                # a higher-priority class (and its shed_requests_t3
                # counter actually exists to read).
                tiers=(max(tier_mix) + 1 if tier_mix else 1),
                edf=bool(tier_mix),
                shed_policy=("oldest" if tier_mix else "reject"))
                if args.e2e_max_waiting > 0 else
                OverloadConfig(tiers=(max(tier_mix) + 1 if tier_mix else 1),
                               edf=bool(tier_mix))),
            # Continuous telemetry + SLO monitoring (ISSUE 6): the BENCH
            # json records attainment and idle-fraction TRAJECTORIES, not
            # just the headline throughput rows. Short burn windows so a
            # few-second bench phase spans several evaluation windows.
            observability=ObservabilityConfig(
                slo_target_ms=float(args.e2e_slo_ms),
                slo_objective=0.99,
                slo_fast_window_s=2.0, slo_slow_window_s=10.0,
                snapshot_interval_s=0.5),
        )
        app = MatchmakingApp(cfg)
        await app.start()
        rt = app.runtime("matchmaking.search")
        rng = np.random.default_rng(3)

        def prefill():
            next_id = 30_000_000
            deficit = args.pool - rt.engine.pool_size()
            while deficit > 0:
                chunk = min(deficit, 8192)
                rt.engine.restore_columns(
                    make_columns(rng, chunk, next_id, time.time()),
                    time.time())
                next_id += chunk
                deficit -= chunk

        async with rt._engine_lock:
            await asyncio.to_thread(prefill)
        pool_start = rt.engine.pool_size()
        log(f"[e2e] pool prefilled to {pool_start}")

        reply_q = "bench.replies"
        app.broker.declare_queue(reply_q)
        lat_ms: list[float] = []
        match_ids: set[str] = set()
        #: Tiered mode: correlation id → assigned tier (the loadgen-side
        #: truth — no tier echo needed from the service) + per-tier
        #: matched latencies for the phase's per-tier p99 rows.
        tier_of_corr: dict[str, int] = {}
        tier_lat: dict[int, list[float]] = {t: [] for t in (tier_mix or ())}

        async def on_reply(delivery) -> None:
            d = json.loads(delivery.body)
            # Only measured-phase arrivals count: warmup players ("w...")
            # that match late carry early x-first-received stamps that
            # would inflate the percentiles; prefilled players have no
            # reply_to at all.
            if (d.get("status") == "matched"
                    and str(d.get("player_id", "")).startswith("e")):
                lat_ms.append(float(d.get("latency_ms", 0.0)))
                if tier_mix:
                    t = tier_of_corr.get(delivery.properties.correlation_id)
                    if t is not None:
                        tier_lat[t].append(float(d.get("latency_ms", 0.0)))
                # Distinct matches, not replies/2: most matches pair one
                # measured arrival with a prefilled (reply-less) player and
                # produce exactly ONE counted reply — halving reply count
                # would undercount the match rate by up to 2x.
                mid = (d.get("match") or {}).get("match_id")
                if mid:
                    match_ids.add(mid)

        app.broker.basic_consume(reply_q, on_reply, prefetch=1_000_000)

        def quiet() -> bool:
            # Drained = nothing buffered at ANY stage: broker queues
            # (request AND reply), handler tasks (deliveries inside a
            # created-but-unstarted handler are invisible to queue_depth),
            # the batcher's open window, a flush in progress (covers the
            # first-window jit compile, during which batcher.depth AND
            # engine.inflight() both read 0), or windows on device.
            return (app.broker.queue_depth(cfg.broker.request_queue) == 0
                    and app.broker.queue_depth(reply_q) == 0
                    and app.broker.handlers_idle()
                    and rt.batcher.depth == 0
                    and rt._flushing == 0
                    and rt.engine.inflight() == 0)

        # Warmup: compile every bucket shape outside the measured phase.
        wrng = np.random.default_rng(4)
        for k, burst in enumerate((8, 40, 160, args.window)):
            r = wrng.normal(1500.0, 300.0, size=burst)
            for j in range(burst):
                app.broker.publish(
                    cfg.broker.request_queue,
                    f'{{"id":"w{k}_{j}","rating":{r[j]:.2f}}}'.encode(),
                    Properties(reply_to=reply_q, correlation_id=f"w{k}_{j}",
                               headers={"x-first-received":
                                        f"{time.time():.6f}"}))
            for _ in range(2400):
                await asyncio.sleep(0.025)
                if quiet():
                    break
        lat_ms.clear()
        log("[e2e] buckets warm; starting measured Poisson phases")

        async def poisson(rate: float, duration: float, tag: str) -> dict:
            """One measured Poisson arrival phase at ``rate`` req/s.
            Exponential gaps, submitted in micro-bursts so the event loop
            isn't woken per message on this 1-core host."""
            lat_ms.clear()
            match_ids.clear()
            tier_of_corr.clear()
            for rows in tier_lat.values():
                rows.clear()
            # Per-PHASE shed accounting: the counters are app-lifetime
            # monotone and every sweep row shares this app — absolute
            # reads would fold the headline + earlier rows' sheds into
            # each later row.
            shed0 = app.metrics.counters.get("shed_requests")
            expired0 = app.metrics.counters.get("expired_requests")
            tier_base = {
                t: (app.metrics.counters.get(f"shed_requests_t{t}"),
                    app.metrics.counters.get(f"expired_requests_t{t}"))
                for t in (tier_mix or ())}
            ratings = rng.normal(1500.0, 300.0,
                                 size=int(rate * duration * 2) + 16)
            tiers = (rng.choice(
                np.fromiter(tier_mix, np.int64, len(tier_mix)),
                size=ratings.size,
                p=np.fromiter(tier_mix.values(), np.float64, len(tier_mix)))
                if tier_mix else None)
            gaps = rng.exponential(1.0 / rate, size=ratings.size)
            t0 = time.perf_counter()
            sched = np.cumsum(gaps)
            i = 0
            while i < ratings.size and sched[i] <= duration:
                now_rel = time.perf_counter() - t0
                # publish everything whose scheduled arrival has passed
                while i < ratings.size and sched[i] <= min(now_rel, duration):
                    pid = f"e{tag}_{i}"
                    body = (f'{{"id":"{pid}","rating":{ratings[i]:.2f}}}'
                            ).encode()
                    headers = {"x-first-received": f"{time.time():.6f}"}
                    if tiers is not None:
                        t = int(tiers[i])
                        stamp_tier(headers, t)
                        tier_of_corr[pid] = t
                    app.broker.publish(
                        cfg.broker.request_queue, body,
                        Properties(reply_to=reply_q, correlation_id=pid,
                                   headers=headers))
                    i += 1
                if i < ratings.size and sched[i] > now_rel:
                    await asyncio.sleep(min(sched[i] - now_rel, 0.005))
            span = time.perf_counter() - t0
            # Snapshot BEFORE the drain: the sustained-rate criterion must
            # count only matches delivered while arrivals were still
            # flowing — replies landing during the drain are backlog being
            # worked off, and counting them against the arrival span would
            # make an oversaturated service look like it kept up.
            matched_in_span = len(lat_ms)
            matches_in_span = len(match_ids)
            # Drain: give in-flight windows + replies time to land (the
            # percentiles DO include drained replies — those are real
            # latencies of this phase's requests).
            drained = False
            for _ in range(400):
                await asyncio.sleep(0.025)
                if quiet():
                    drained = True
                    break
            if not drained:
                log(f"[e2e {tag}] WARNING: backlog not drained in 10 s — "
                    "later rows may be contaminated")
            arr = (np.sort(np.asarray(lat_ms)) if lat_ms
                   else np.array([0.0]))
            row = {
                "e2e_offered_req_s": rate,
                "e2e_requests": i,
                "e2e_rate_req_s": round(i / span, 1),
                "e2e_players_matched": len(lat_ms),
                "e2e_matched_per_s": round(matched_in_span / span, 1),
                "e2e_matches_per_sec": round(matches_in_span / span, 1),
                "e2e_p50_ms": round(float(np.percentile(arr, 50)), 3),
                "e2e_p99_ms": round(float(np.percentile(arr, 99)), 3),
                "e2e_drained": drained,
                "e2e_pool_end": rt.engine.pool_size(),
            }
            if args.e2e_max_waiting > 0:
                row["e2e_shed"] = int(
                    app.metrics.counters.get("shed_requests") - shed0)
                row["e2e_expired"] = int(
                    app.metrics.counters.get("expired_requests") - expired0)
            if tier_mix:
                # Per-tier p99/shed/expired (ISSUE 7): the row that shows
                # ordered degradation — tier 0 holding while the lowest
                # tier absorbs the shedding.
                row["e2e_tiers"] = {
                    str(t): {
                        "offered": (int((tiers[:i] == t).sum())
                                    if tiers is not None else 0),
                        "matched": len(tier_lat[t]),
                        "p99_ms": (round(float(np.percentile(
                            np.asarray(tier_lat[t]), 99)), 3)
                            if tier_lat[t] else None),
                        "shed": int(app.metrics.counters.get(
                            f"shed_requests_t{t}") - tier_base[t][0]),
                        "expired": int(app.metrics.counters.get(
                            f"expired_requests_t{t}") - tier_base[t][1]),
                    }
                    for t in sorted(tier_mix)
                }
            return row

        headline = await poisson(float(args.e2e_rate),
                                 float(args.e2e_seconds), "h")
        headline["e2e_pool_start"] = pool_start

        # Saturation sweep: escalate offered load to find the knee of the
        # single-process service (round-4 verdict #1: the engine does 64k
        # matches/s but the service was only proven at ~6k offered). The
        # knee is the highest offered rate the service still clears at
        # ≥90% (matched players/s vs offered arrivals/s).
        sweep_rows = []
        knee = None
        if args.e2e_rates:
            for r in (float(x) for x in args.e2e_rates.split(",")):
                async with rt._engine_lock:
                    await asyncio.to_thread(prefill)
                row = await poisson(r, float(args.e2e_sweep_seconds),
                                    f"k{int(r)}")
                log(f"[e2e sweep] {row}")
                sweep_rows.append(row)
                if row["e2e_matched_per_s"] >= 0.9 * r:
                    knee = max(knee or 0.0, r)

        # Snapshot the final /metrics-style report into the BENCH json:
        # future rounds get stage-level trajectories (per-stage latency
        # histograms, engine counters, broker stats), not just the headline
        # matches/s + p99 rows.
        from matchmaking_tpu.service.observability import build_report

        app.sample_telemetry()  # final trajectory point before teardown
        metrics_report = build_report(app)
        out = dict(headline)
        if sweep_rows:
            out["e2e_sweep"] = sweep_rows
            out["e2e_knee_req_s"] = knee
        # SLO attainment + device idle fraction (ISSUE 6): the measurement
        # substrate the hot-path rewrite and elastic placement consume.
        attr = app.attribution.snapshot()["queues"].get(
            cfg.broker.request_queue, {})
        out["e2e_slo_target_ms"] = float(args.e2e_slo_ms)
        out["e2e_slo_attainment"] = attr.get("slo_attainment")
        out["e2e_wait_fraction"] = attr.get("wait_fraction")
        # Per-category attribution shares (ISSUE 9): where the e2e span
        # went — publish_lag/encode/middleware/ingress vs device work —
        # recorded into the BENCH json so the hot-path trajectory
        # ("publish_lag + middleware/ingress share reduced") is diffable
        # round over round, not just the headline rate.
        out["e2e_attribution"] = {
            name: {"kind": cat.get("kind"), "share": cat.get("share"),
                   "p99_ms": cat.get("p99_ms")}
            for name, cat in (attr.get("categories") or {}).items()
        }
        # Consume/decode ingest share (ISSUE 12): the broker-consume +
        # wire-decode WORK fraction of the settled span — the number the
        # consume_batch seam exists to shrink. Recorded top-level so
        # bench_diff gates it direction-aware (lower is better).
        cats = attr.get("categories") or {}
        out["e2e_consume_share"] = round(
            sum((cats.get(c) or {}).get("share") or 0.0
                for c in ("consume", "decode")), 4)
        if hasattr(rt.engine, "util_report"):
            u = rt.engine.util_report()
            out["e2e_idle_fraction"] = u["idle_fraction"]
            out["e2e_effective_occupancy"] = u["effective_occupancy"]
        out["telemetry"] = app.telemetry.snapshot(
            limit=240, prefixes=("idle_frac", "slo_good", "slo_total",
                                 "pool_size", "stage_total_p99_ms",
                                 "effective_occupancy", "batch_fill"))
        out["metrics_report"] = metrics_report
        await app.stop()
        return out

    return asyncio.run(run())


def bench_quality_frontier(args) -> dict:
    """The measured quality-vs-latency frontier (ISSUE 8; ``--e2e-quality``).

    Sweeps the queue's ``rating_threshold`` (and optionally
    ``widen_per_sec``) across fresh single-queue apps at a fixed offered
    load, and records each point's match-quality distribution
    (p10/p50/mean), engine-observed wait-at-match p99, client-observed
    latency p99, and the per-rating-bucket disparity gaps — the Cinder-style
    fast-vs-fair tradeoff as ``e2e_frontier`` rows in the BENCH json.

    A STRICTER threshold buys closer-rated matches (mean rating distance
    falls) at the cost of longer waits; the monotone flags compare the
    extremes so a frontier that fails to trade is visible in the artifact.
    This is the baseline any future pluggable match-objective kernel
    (ROADMAP) must beat: better quality at equal wait, or equal quality
    faster.
    """
    import asyncio

    from matchmaking_tpu.config import (
        BatcherConfig,
        BrokerConfig,
        Config,
        EngineConfig,
        ObservabilityConfig,
        QueueConfig,
    )
    from matchmaking_tpu.service.app import MatchmakingApp
    from matchmaking_tpu.service.loadgen import offered_load

    thresholds = [float(x) for x in args.e2e_quality_thresholds.split(",")
                  if x.strip()]
    rate = float(args.e2e_quality_rate)
    seconds = float(args.e2e_quality_seconds)
    widen = float(args.e2e_quality_widen)
    # Small geometry on purpose: the frontier is a SHAPE measurement (how
    # quality trades against wait), not a throughput row — it must also
    # complete on the CPU-mesh fallback.
    capacity = min(args.capacity, 8192)

    async def point(threshold: float, spec: bool = False) -> dict:
        cfg = Config(
            queues=(QueueConfig(rating_threshold=threshold,
                                widen_per_sec=widen,
                                max_threshold=max(400.0, threshold),
                                send_queued_ack=False),),
            engine=EngineConfig(backend="tpu", pool_capacity=capacity,
                                pool_block=min(args.pool_block, capacity),
                                batch_buckets=(16, 64, 256), top_k=8,
                                pipeline_depth=args.depth,
                                warm_start=True,
                                # Speculation axis (ISSUE 16): the same
                                # point with gap-cycle speculation on.
                                spec_formation=spec,
                                spec_interval_ms=10.0),
            batcher=BatcherConfig(max_batch=256, max_wait_ms=3.0),
            broker=BrokerConfig(prefetch=8192),
            observability=ObservabilityConfig(snapshot_interval_s=0.0,
                                              quality_report_every=4),
        )
        app = MatchmakingApp(cfg)
        await app.start()
        rt = app.runtime(cfg.broker.request_queue)
        res = await offered_load(
            app, cfg.broker.request_queue, rate=rate, duration=seconds,
            seed=11, quality_stats=True,
            rating_sigma=float(args.e2e_quality_sigma))
        # Exact engine totals for the disparity read: flush forces the
        # device-accumulator snapshot.
        async with rt._engine_lock:
            await asyncio.to_thread(rt.engine.flush)
        rep = (rt.engine.quality_report()
               if hasattr(rt.engine, "quality_report") else None) or {}
        sr = (rt.engine.spec_report()
              if spec and hasattr(rt.engine, "spec_report") else None) or {}
        await app.stop()
        qs = res.get("quality", {})
        return {
            "threshold": threshold,
            "spec_formation": spec,
            "spec_hit_rate": sr.get("spec_hit_rate"),
            "widen_per_sec": widen,
            "offered_req_s": rate,
            "matched": qs.get("matched", 0),
            "matched_per_s": res.get("matched_per_s"),
            "quality_mean": qs.get("quality_mean"),
            "quality_p10": qs.get("quality_p10"),
            "quality_p50": qs.get("quality_p50"),
            "wait_at_match_ms_p50": qs.get("waited_ms_p50"),
            "wait_at_match_ms_p99": qs.get("waited_ms_p99"),
            "latency_ms_p99": qs.get("latency_ms_p99"),
            "wait_gap_ms_mean": qs.get("wait_gap_ms_mean"),
            "spread_mean": rep.get("spread_mean"),
            "engine_wait_p90_s": rep.get("wait_p90_s"),
            "quality_disparity": rep.get("disparity", {}).get("quality_gap"),
            "wait_p90_disparity_s": rep.get("disparity",
                                            {}).get("wait_p90_gap_s"),
            "sent": res.get("sent", 0),
        }

    rows = []
    for thr in thresholds:
        row = asyncio.run(point(thr))
        log(f"[e2e-quality thr={thr:g}] {row}")
        rows.append(row)
    out: dict = {"e2e_frontier": rows}
    if args.e2e_quality_spec:
        # Speculation axis (ISSUE 16 satellite): the SAME sweep with
        # gap-cycle speculation on, kept in a separate row list so
        # bench_diff matches spec-on points against spec-on baselines.
        # The in-run gate: fairness must not pay for the overlap —
        # per-rating-bucket quality disparity at each threshold with
        # speculation on must stay within 10% (plus a small absolute
        # slack for near-zero gaps) of the spec-off point.
        spec_rows = []
        for thr in thresholds:
            row = asyncio.run(point(thr, spec=True))
            log(f"[e2e-quality thr={thr:g} spec=on] {row}")
            spec_rows.append(row)
        out["e2e_frontier_spec"] = spec_rows
        off_by_thr = {r["threshold"]: r for r in rows}
        gate: bool | None = None
        for sr_row in spec_rows:
            base = off_by_thr.get(sr_row["threshold"])
            if base is None:
                continue
            d_off = base.get("quality_disparity")
            d_on = sr_row.get("quality_disparity")
            if d_off is None or d_on is None:
                continue
            ok = d_on <= d_off + max(0.10 * d_off, 0.02)
            gate = ok if gate is None else (gate and ok)
            if not ok:
                log(f"[e2e-quality thr={sr_row['threshold']:g}] "
                    f"disparity regressed with speculation on: "
                    f"{d_off:.4f} -> {d_on:.4f}")
        if gate is not None:
            out["e2e_frontier_spec_disparity_ok"] = gate
    # Monotone-tradeoff flags between the sweep extremes (sorted by
    # threshold): stricter matching must buy a smaller mean rating
    # distance and cost a longer wait, or the frontier didn't trade.
    ordered = sorted((r for r in rows if r["matched"]),
                     key=lambda r: r["threshold"])
    if len(ordered) >= 2:
        lo, hi = ordered[0], ordered[-1]
        if lo.get("spread_mean") is not None and hi.get("spread_mean") is not None:
            out["e2e_frontier_spread_monotone"] = (
                lo["spread_mean"] <= hi["spread_mean"])
        if (lo.get("wait_at_match_ms_p50") is not None
                and hi.get("wait_at_match_ms_p50") is not None):
            out["e2e_frontier_wait_monotone"] = (
                lo["wait_at_match_ms_p50"] >= hi["wait_at_match_ms_p50"])
    return out


def bench_multiproc(args) -> dict:
    """Multi-process ingress scaling: N supervised self-driving workers
    (service/multiproc.WorkerSupervisor + service/loadgen), each running
    the FULL ingress path (broker → decode → middleware → batcher → engine
    → publish) against its own queue partition. No RabbitMQ exists in this
    environment, so workers drive themselves instead of sharing a network
    broker (loadgen.py docstring).

    Interpretation on THIS bench host (1 core): the aggregate is
    core-bound by construction — the N=1 row IS the per-process ingress
    ceiling, and the N=2 row pins that partitioned share-nothing workers
    add no coordination overhead beyond the core they fight over. On an
    M-core deployment the per-worker ceiling multiplies by min(M, N); the
    architecture (one pool owner per queue, AMQP routing by queue name)
    has no cross-worker communication to cap it."""
    import subprocess
    import sys
    import tempfile

    from matchmaking_tpu.config import Config, EngineConfig, QueueConfig
    from matchmaking_tpu.service.multiproc import WorkerSupervisor

    rows = []
    for n in (1, 2):
        cfg = Config(
            queues=tuple(QueueConfig(name=f"lg{i}", send_queued_ack=False)
                         for i in range(n)),
            engine=EngineConfig(backend="cpu", pool_capacity=4096),
        )
        outs = []
        extra = {}
        for i in range(n):
            fd, path = tempfile.mkstemp(prefix=f"mm_lg{i}_", suffix=".json")
            os.close(fd)
            outs.append(path)
            extra[i] = {
                "MM_LOADGEN_RATE": str(args.mp_rate),
                "MM_LOADGEN_SECONDS": str(args.mp_seconds),
                "MM_LOADGEN_DEADLINE_MS": str(args.mp_deadline_ms),
                "MM_LOADGEN_OUT": path,
                "JAX_PLATFORMS": "cpu",
            }
        sup = WorkerSupervisor(
            cfg, n,
            command=[sys.executable, "-m", "matchmaking_tpu.service.loadgen"],
            extra_env=extra)
        for w in sup.workers:
            # Workers are host-only: skip the axon TPU-relay dial that the
            # machine-wide sitecustomize adds to every interpreter start.
            w.env.pop("PALLAS_AXON_POOL_IPS", None)
        sup.start()
        try:
            for w in sup.workers:
                w.proc.wait(timeout=args.mp_seconds + 60)
        except subprocess.TimeoutExpired:
            log(f"[multiproc] worker fleet n={n} timed out")
        finally:
            sup.stop()
        results = []
        for path in outs:
            try:
                with open(path) as f:
                    results.append(json.load(f))
            except (OSError, json.JSONDecodeError):
                pass
            try:
                os.unlink(path)
            except OSError:
                pass
        row = {
            "workers": n,
            "completed": len(results),
            "offered_req_s_per_worker": float(args.mp_rate),
            "agg_sent_req_s": round(sum(r["sent_req_s"] for r in results), 1),
            "agg_matched_per_s": round(
                sum(r["matched_per_s"] for r in results), 1),
        }
        log(f"[multiproc] {row}")
        rows.append(row)
    return {"multiproc": rows, "multiproc_host_cores": os.cpu_count()}


def comms_accounting_rows(*, capacity: int = 65_536, team_size: int = 5,
                          frontier_k: int = 1024,
                          shard_counts=(2, 4, 8)) -> list[dict]:
    """The sharded team/role comms phase (ISSUE 1 tentpole artifact): for
    each mesh size D, build BOTH sharded paths and report per-device
    per-step ICI bytes + formation rows — allgather-replicated is O(P)
    regardless of D, the ppermute ring frontier is O(P/D + K·D). Each row
    also EXECUTES one step per path on an identical seeded pool and
    records whether the packed outputs are byte-identical, so the table
    is a measured artifact, not prose. Runs on any backend with >= D
    devices (tests/CI: the 8-virtual-device CPU mesh; set
    XLA_FLAGS=--xla_force_host_platform_device_count=8)."""
    import jax
    import jax.numpy as jnp

    from matchmaking_tpu.engine.role_kernels import ShardedRoleKernelSet
    from matchmaking_tpu.engine.sharded import pool_mesh
    from matchmaking_tpu.engine.teams import ShardedTeamKernelSet

    if frontier_k <= 0:
        raise SystemExit(
            "--comms needs --comms-frontier-k > 0: the comms phase compares "
            "the ring path against the allgather fallback, and the kernel "
            "sets only compile the ring step when frontier_k is set")
    n_dev = len(jax.devices())
    rows = []
    for D in shard_counts:
        if D > n_dev:
            rows.append({"n_shards": D, "skipped": f"only {n_dev} devices"})
            continue
        for family in ("team", "role"):
            if family == "team":
                ks = ShardedTeamKernelSet(
                    capacity=capacity, team_size=team_size,
                    widen_per_sec=0.0, max_threshold=400.0,
                    mesh=pool_mesh(D), max_matches=64,
                    frontier_k=frontier_k)
                pack_rows, mask_of = 9, None
            else:
                ks = ShardedRoleKernelSet(
                    capacity=capacity, team_size=team_size,
                    role_slots=("tank", "healer", "dps", "dps", "dps"),
                    widen_per_sec=0.0, max_threshold=400.0,
                    mesh=pool_mesh(D), max_matches=64,
                    frontier_k=frontier_k)
                pack_rows, mask_of = 10, ks.mask_of
            acct = ks.comms_accounting()
            # One executed step per path on an identical seeded pool:
            # occupancy under K per shard, so ring must be bit-identical.
            P = ks.capacity
            rng = np.random.default_rng(17)
            n_active = min(frontier_k, ks.local_capacity, 512)
            arrays = {
                "rating": np.zeros(P, np.float32),
                "rd": np.zeros(P, np.float32),
                "region": np.zeros(P, np.int32),
                "mode": np.zeros(P, np.int32),
                "threshold": np.full(P, 120.0, np.float32),
                "enqueue_t": np.zeros(P, np.float32),
                "active": np.zeros(P, bool),
            }
            arrays["rating"][:n_active] = rng.normal(1500.0, 60.0, n_active)
            arrays["region"][:n_active] = 1
            arrays["mode"][:n_active] = 1
            arrays["active"][:n_active] = True
            if mask_of is not None:
                arrays["role_mask"] = np.zeros(P, np.int32)
                arrays["role_mask"][:n_active] = [
                    [mask_of(("tank",)), mask_of(("healer",)),
                     mask_of(("dps",)), mask_of(())][i % 4]
                    for i in range(n_active)]
            packed = np.zeros((pack_rows, 16), np.float32)
            packed[0] = float(P)
            packed[pack_rows - 1] = 1.0
            t0 = time.perf_counter()
            _, out_rep = ks.search_step_packed(
                ks.place_pool(arrays), jnp.asarray(packed))
            out_rep = np.asarray(out_rep)
            t_rep = time.perf_counter() - t0
            t0 = time.perf_counter()
            _, out_ring = ks.search_step_packed_ring(
                ks.place_pool(arrays), jnp.asarray(packed))
            out_ring = np.asarray(out_ring)
            t_ring = time.perf_counter() - t0
            rows.append({
                "family": family, "n_shards": D, "capacity": P,
                "frontier_k": ks.frontier_k,
                "allgather_ici_recv_bytes": acct["allgather"]["ici_recv_bytes"],
                "ring_ici_recv_bytes": acct["ring"]["ici_recv_bytes"],
                "allgather_formation_rows": acct["allgather"]["formation_rows"],
                "ring_formation_rows": acct["ring"]["formation_rows"],
                "outputs_bit_identical": bool(
                    np.array_equal(out_rep, out_ring)),
                "matches_formed": int((out_rep[0] < P).sum()),
                "step_ms_allgather_cold": round(t_rep * 1e3, 1),
                "step_ms_ring_cold": round(t_ring * 1e3, 1),
            })
            log(f"[comms] {family} D={D}: gather "
                f"{acct['allgather']['ici_recv_bytes']} B vs ring "
                f"{acct['ring']['ici_recv_bytes']} B, formation rows "
                f"{acct['allgather']['formation_rows']} vs "
                f"{acct['ring']['formation_rows']}, bit_identical="
                f"{rows[-1]['outputs_bit_identical']}")
    return rows


def run_cpu_fallback(args) -> None:
    """ROADMAP carry-over (BENCH_r05 recorded ``backend_unavailable`` and
    lost the whole round): when the TPU init probe hangs/fails past its
    retry budget, fall back to the CPU-mesh comms/e2e configs instead of
    aborting — a dead backend still yields a partial trajectory point,
    tagged ``backend: cpu-fallback`` so the driver can tell a degraded
    point from a real TPU one. Prints exactly ONE JSON line, rc 0."""
    import sys

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # 8 virtual host devices so the comms phase's sharded kernel sets
        # have a mesh to build against (same trick as tests/conftest.py).
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    if ("jax" in sys.modules
            and os.environ.get("MM_BENCH_CPU_FALLBACK") != "1"):
        # jax was already imported against the dead backend in this process
        # (probe green, in-process init failed) — its global backend state
        # cannot be re-pointed. Exec a clean interpreter pinned to CPU.
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["MM_BENCH_CPU_FALLBACK"] = "1"
        log("[fallback] re-exec with JAX_PLATFORMS=cpu for a clean backend")
        sys.stdout.flush()
        sys.stderr.flush()
        os.execv(sys.executable, [sys.executable] + sys.argv)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        devices = jax.devices()
    except Exception as e:
        log(f"[fallback] CPU backend init failed too: {e!r}")
        # Structured abort (ISSUE 12 satellite — what burned BENCH_r05):
        # the round records WHY it aborted and what it was configured to
        # measure, so the driver archives an explainable partial artifact
        # and bench_diff skips the round instead of failing on nulls.
        print(json.dumps({
            "metric": f"matches/sec @ {args.pool}-player pool (1v1 ELO)",
            "value": None, "unit": "matches/sec", "vs_baseline": None,
            "error": "backend_unavailable",
            "abort_reason": "backend_unavailable",
            "abort_detail": f"cpu fallback init failed: {e!r}",
            "abort_config": {"pool": args.pool, "window": args.window,
                             "depth": args.depth,
                             "init_retries": args.init_retries},
        }), flush=True)
        return
    log(f"[fallback] TPU unavailable — running CPU-mesh configs on "
        f"{len(devices)} host devices")
    # Scale the geometry to the host: the point of the fallback row is the
    # trajectory SHAPE (e2e service path, attainment, idle fraction, comms
    # accounting), not absolute device throughput.
    args.pool = min(args.pool, 4000)
    args.capacity = min(args.capacity, 8192)
    args.pool_block = min(args.pool_block, 2048)
    args.window = min(args.window, 512)
    args.depth = min(args.depth, 2)
    args.readback_group = 1
    args.e2e_rate = min(args.e2e_rate, 1000.0)
    args.e2e_seconds = min(args.e2e_seconds, 4.0)
    args.e2e_rates = ""
    out: dict = {
        "metric": (f"e2e matched players/sec @ {args.pool}-player pool "
                   "(cpu-fallback)"),
        "value": None,
        "unit": "players/sec",
        "vs_baseline": None,
        "backend": "cpu-fallback",
        "tpu_error": "backend_unavailable",
    }
    if not args.fallback_skip_comms and len(devices) >= 2:
        try:
            out["comms"] = comms_accounting_rows(
                capacity=8192, team_size=5, frontier_k=256,
                shard_counts=(2,))
        except Exception as e:
            log(f"[fallback] comms phase failed: {e!r}")
    try:
        e2e = bench_e2e(args)
        out.update(e2e)
        out["value"] = e2e.get("e2e_matched_per_s")
    except Exception as e:
        log(f"[fallback] e2e phase failed: {e!r}")
        out["error"] = "cpu_fallback_failed"
        # Partial-result abort record: the comms rows (if any) above stay
        # in the artifact; the reason travels with them.
        out["abort_reason"] = "cpu_fallback_failed"
        out["abort_detail"] = repr(e)
    if args.e2e_quality:
        # The frontier is a shape measurement — it runs on the CPU mesh
        # unchanged (the acceptance gate for ISSUE 8 reads it here).
        try:
            out.update(bench_quality_frontier(args))
        except Exception as e:
            log(f"[fallback] e2e-quality phase failed: {e!r}")
    if args.spec_ab:
        # Turnaround deltas, not absolute throughput — the spec A/B is
        # meaningful on the CPU mesh too. A failure leaves the spec_*
        # columns absent; bench_diff skips one-sided metrics.
        try:
            out.update(bench_spec_ab(args))
        except Exception as e:
            log(f"[fallback] spec-ab phase failed: {e!r}")
    print(json.dumps(out), flush=True)


def bench_consume_ab(args) -> dict:
    """Consume-share A/B (ISSUE 12 acceptance): the SAME seeded offered
    load through two fresh single-queue apps — ``consume_batch`` on vs
    off — recording each run's consume+decode ingest work (seconds, and
    share of the settled span). The acceptance bar is the ON config's
    consume/decode work per request down ≥ 2× at fixed offered load;
    ``work_reduction_x`` is that ratio, measured, in the artifact."""
    import asyncio

    from matchmaking_tpu.config import (
        BatcherConfig,
        BrokerConfig,
        Config,
        EngineConfig,
        ObservabilityConfig,
        QueueConfig,
    )
    from matchmaking_tpu.service.app import MatchmakingApp
    from matchmaking_tpu.service.loadgen import offered_load

    async def one(consume_batch: bool) -> dict:
        cfg = Config(
            queues=(QueueConfig(rating_threshold=100.0,
                                send_queued_ack=False),),
            engine=EngineConfig(
                backend="tpu", pool_capacity=8192, pool_block=2048,
                batch_buckets=(16, 64, 256), top_k=8,
                pipeline_depth=min(args.depth, 2), warm_start=True),
            batcher=BatcherConfig(max_batch=256, max_wait_ms=3.0),
            broker=BrokerConfig(prefetch=8192,
                                consume_batch=consume_batch),
            observability=ObservabilityConfig(snapshot_interval_s=0.0),
        )
        app = MatchmakingApp(cfg)
        await app.start()
        try:
            res = await offered_load(
                app, cfg.broker.request_queue,
                rate=float(args.e2e_ab_rate),
                duration=float(args.e2e_ab_seconds), seed=7)
            attr = app.attribution.snapshot()["queues"].get(
                cfg.broker.request_queue, {})
            cats = attr.get("categories") or {}
            work_s = sum((cats.get(c) or {}).get("total_s") or 0.0
                         for c in ("consume", "decode"))
            sent = max(1, res.get("sent", 1))
            return {
                "consume_batch": consume_batch,
                "share": round(sum(
                    (cats.get(c) or {}).get("share") or 0.0
                    for c in ("consume", "decode")), 4),
                "work_s": round(work_s, 6),
                "work_us_per_req": round(work_s / sent * 1e6, 3),
                "sent": res.get("sent"),
                "matched": res.get("players_matched"),
            }
        finally:
            await app.stop()

    async def run() -> dict:
        on = await one(True)
        off = await one(False)
        ratio = (off["work_s"] / on["work_s"]) if on["work_s"] else None
        return {"e2e_consume_ab": {
            "on": on, "off": off,
            "work_reduction_x": round(ratio, 2) if ratio else None,
            "rate_req_s": float(args.e2e_ab_rate),
            "seconds": float(args.e2e_ab_seconds),
        }}

    return asyncio.run(run())


def bench_spec_ab(args) -> dict:
    """Speculative-formation A/B (ISSUE 16 acceptance; ``--spec-ab``): the
    SAME seeded offered load through two fresh single-queue apps —
    ``spec_formation`` on vs off — at a widening-driven operating point
    (threshold strict at admit, ``widen_per_sec`` grows feasibility while
    players sit resident, rescan interval deliberately coarse). In the
    OFF run a pool-resident pair that becomes feasible mid-gap waits for
    the next rescan tick; in the ON run the gap loop has already
    precomputed the pairing and the cut commits it in O(delta) — the
    turnaround (engine-observed wait-at-match) p50/p99 must fall at the
    SAME offered load and the SAME window wait. The row also records the
    speculation economics: ``spec_hit_rate`` (validated commits over all
    speculation outcomes) and ``spec_wasted_step_fraction`` (speculative
    device steps whose windows were discarded — the overlap price).
    scripts/bench_diff.py gates all four direction-aware; on a chip-less
    abort the keys are simply absent and the gate skips them."""
    import asyncio

    from matchmaking_tpu.config import (
        BatcherConfig,
        BrokerConfig,
        Config,
        EngineConfig,
        ObservabilityConfig,
        QueueConfig,
    )
    from matchmaking_tpu.service.app import MatchmakingApp
    from matchmaking_tpu.service.loadgen import offered_load

    async def one(spec: bool) -> dict:
        cfg = Config(
            queues=(QueueConfig(
                # Strict at admit, feasible while resident: the regime
                # where gap-cycle speculation has work to steal.
                rating_threshold=25.0, widen_per_sec=50.0,
                max_threshold=400.0,
                # Coarse rescan on BOTH sides — the A/B isolates the
                # speculative overlap, not a rescan-frequency change.
                rescan_interval_s=0.5,
                send_queued_ack=False),),
            engine=EngineConfig(
                backend="tpu", pool_capacity=4096, pool_block=1024,
                batch_buckets=(16, 64, 256), top_k=8,
                pipeline_depth=min(args.depth, 2), warm_start=True,
                spec_formation=spec, spec_max_steps=2,
                spec_interval_ms=10.0),
            batcher=BatcherConfig(max_batch=256, max_wait_ms=3.0),
            broker=BrokerConfig(prefetch=8192),
            observability=ObservabilityConfig(snapshot_interval_s=0.0),
        )
        app = MatchmakingApp(cfg)
        await app.start()
        rt = app.runtime(cfg.broker.request_queue)
        res = await offered_load(
            app, cfg.broker.request_queue, rate=float(args.spec_ab_rate),
            duration=float(args.spec_ab_seconds), seed=13,
            quality_stats=True, rating_sigma=200.0)
        sr = (rt.engine.spec_report()
              if hasattr(rt.engine, "spec_report") else None) or {}
        await app.stop()
        qs = res.get("quality", {})
        return {
            "spec_formation": spec,
            "sent": res.get("sent"),
            "matched": res.get("players_matched"),
            "turnaround_ms_p50": qs.get("waited_ms_p50"),
            "turnaround_ms_p99": qs.get("waited_ms_p99"),
            "spec_hit": sr.get("spec_hit"),
            "spec_miss": sr.get("spec_miss"),
            "spec_wasted": sr.get("spec_wasted"),
            "spec_hit_rate": sr.get("spec_hit_rate"),
            "spec_wasted_step_fraction": sr.get(
                "spec_wasted_step_fraction"),
        }

    async def run() -> dict:
        on = await one(True)
        off = await one(False)
        return {
            "e2e_spec_ab": {
                "on": on, "off": off,
                "rate_req_s": float(args.spec_ab_rate),
                "seconds": float(args.spec_ab_seconds),
            },
            # Top-level scalars so bench_diff compares them like any
            # other headline (absent when the phase aborts → skipped).
            "spec_turnaround_ms_p50": on["turnaround_ms_p50"],
            "spec_turnaround_ms_p99": on["turnaround_ms_p99"],
            "spec_hit_rate": on["spec_hit_rate"],
            "spec_wasted_step_fraction": on["spec_wasted_step_fraction"],
        }

    return asyncio.run(run())


def bench_pool_scale(args) -> list:
    """Sub-O(P) formation sweep (ISSUE 14): a hierarchical rating-bucketed
    engine at growing synthetic pool scales (default 100k/300k/1M), one
    row per scale with throughput + ``formation_touched_frac`` — the pool
    slots each window lane's formation actually scored over the flat
    step's O(P). Geometry per scale: capacity ≈ 4/3 × pool rounded to the
    block, one rating band per block, span budget ~33% of the blocks
    (``_bucketed_geometry``). The pool is seeded by building the device
    arrays straight from the columnar mirror (one vectorized pass + an
    exact index rebuild) — admitting a million players through O(P)
    device admits would measure the fill, not formation.
    """
    import jax
    import jax.numpy as jnp

    from matchmaking_tpu.config import Config, EngineConfig, QueueConfig
    from matchmaking_tpu.core.pool import PlayerPool
    from matchmaking_tpu.engine.interface import make_engine

    scales = [int(s) for s in args.pool_scale.split(",") if s]
    window = args.pool_scale_window
    rows = []
    for pool_target in scales:
        # ~64+ blocks per scale: bucket resolution is what the span math
        # converts into a small touched fraction.
        pool_block = min(args.pool_block,
                         max(1024, 1 << (pool_target // 96).bit_length()))
        capacity = ((pool_target * 4 // 3 + pool_block - 1)
                    // pool_block) * pool_block
        geo = _bucketed_geometry(capacity, pool_block, window)
        cfg = Config(
            queues=(QueueConfig(rating_threshold=100.0),),
            engine=EngineConfig(
                backend="tpu", pool_capacity=capacity,
                pool_block=pool_block,
                batch_buckets=(16, 64, 256, window), top_k=8,
                readback_group=1, **geo,
            ),
        )
        engine = make_engine(cfg, cfg.queues[0])
        rng = np.random.default_rng(14)
        log(f"[pool-scale {pool_target}] capacity={capacity} "
            f"blocks={capacity // pool_block} "
            f"span_blocks={geo['prune_window_blocks']}")
        # Vectorized fill: mirror first (banded slot placement), then the
        # device columns in ONE device_put + one exact index rebuild.
        t0 = engine._rel_base(0.0)
        filled = 0
        while filled < pool_target:
            chunk = min(pool_target - filled, 65_536)
            engine.pool.allocate_columns(
                make_columns(rng, chunk, filled, 0.0))
            filled += chunk
        occ = engine.pool.waiting_slots()
        arrays = PlayerPool.empty_device_arrays(capacity)
        arrays["rating"][occ] = engine.pool.m_rating[occ]
        arrays["rd"][occ] = engine.pool.m_rd[occ]
        arrays["region"][occ] = engine.pool.m_region[occ]
        arrays["mode"][occ] = engine.pool.m_mode[occ]
        arrays["threshold"][occ] = engine.pool.m_threshold[occ]
        arrays["enqueue_t"][occ] = (engine.pool.m_enqueued[occ]
                                    - t0).astype(np.float32)
        arrays["active"][occ] = True
        arrays.update(engine.kernels.init_index_arrays())
        engine._dev_pool = engine.kernels.index_rebuild(
            {k: jnp.asarray(v) for k, v in arrays.items()})
        jax.block_until_ready(engine._dev_pool)
        log(f"[pool-scale {pool_target}] pool seeded "
            f"({engine.pool_size()} waiting)")
        # Steady-occupancy stream: no refill (pool_target=0 disables it) —
        # a few windows drain only matched players, << pool.
        mps, lats, total = run_engine_pipelined(
            engine, rng, pool_target=0, window=window, warmup=2,
            measured=args.pool_scale_windows, depth=2,
            label=f"pool-scale {pool_target}",
            # Fresh id space: the fill consumed p0..p<pool>, and duplicate
            # ids would be dedup-dropped into empty windows.
            gen=lambda r, n, sid, now: make_columns(
                r, n, sid + pool_target, now))
        rep = engine.formation_report() or {}
        lat_ms = np.sort(np.asarray(lats)) * 1e3
        rows.append({
            "pool": pool_target,
            "capacity": capacity,
            "blocks": capacity // pool_block,
            "span_blocks": geo["prune_window_blocks"],
            "window": window,
            "matches_per_sec": round(mps, 1),
            "p99_ms": (float(np.percentile(lat_ms, 99))
                       if lat_ms.size else None),
            "total_matches": total,
            "formation_touched_frac": rep.get("formation_touched_frac"),
            "formation_windows": rep.get("windows"),
        })
        log(f"[pool-scale {pool_target}] {rows[-1]}")
    return rows


def bench_cpu_oracle(args) -> dict:
    """Reference-semantics oracle at the reference's ~2k-player scale."""
    from matchmaking_tpu.config import Config, QueueConfig
    from matchmaking_tpu.engine.interface import make_engine

    cfg = Config(queues=(QueueConfig(rating_threshold=100.0),))
    engine = make_engine(cfg, cfg.queues[0])
    rng = np.random.default_rng(1)
    mps, lats, total = run_engine(
        engine, rng, pool_target=args.cpu_pool, window=64,
        warmup=2, measured=args.cpu_windows, label="cpu")
    lat_ms = np.sort(np.asarray(lats)) * 1e3
    return {
        "matches_per_sec": mps,
        "p99_ms": float(np.percentile(lat_ms, 99)),
        "total_matches": total,
        "pool": args.cpu_pool,
    }


def bench_placement_soak(args) -> dict:
    """Elastic placement soak (ISSUE 11): seeded load streams through a
    hot queue while the control plane executes a scripted
    migrate → promote(D=2) → demote(D=1) → migrate-back cycle through the
    SAME audited path policy decisions take (PlacementController.force).
    Measures the migration blackout (max/mean, from the decision ring)
    and proves delivery accounting across the moves: zero lost (every
    submitted player matched or still waiting at the end) and zero
    duplicated terminal responses.

    Runs on whatever backend is initialized; on a CPU box the caller
    forces a 4-virtual-device host mesh, so the promote leg exercises the
    real sharded kernel set.  scripts/bench_diff.py gates
    placement_blackout_ms_max / placement_lost / placement_dup
    direction-aware (lower is better)."""
    import asyncio

    async def run() -> dict:
        from matchmaking_tpu.config import (
            BatcherConfig,
            Config,
            EngineConfig,
            OverloadConfig,
            PlacementConfig,
            QueueConfig,
        )
        from matchmaking_tpu.service.app import MatchmakingApp
        from matchmaking_tpu.service.broker import Properties

        import jax

        n_dev = min(4, len(jax.devices()))
        if n_dev < 2:
            # An explicit error row, not a vacuous clean run: with one
            # device every scripted move would be refused and the
            # lost/dup/blackout gates would pass while measuring nothing.
            return {"error": "placement_soak_needs_2_devices",
                    "placement_devices": n_dev}
        window = int(args.placement_window)
        cfg = Config(
            queues=(QueueConfig(name="soak.hot", rating_threshold=200.0,
                                send_queued_ack=False),
                    QueueConfig(name="soak.cold", rating_threshold=200.0,
                                send_queued_ack=False)),
            engine=EngineConfig(backend="tpu",
                                pool_capacity=max(4 * window, 1024),
                                pool_block=max(window, 256),
                                batch_buckets=(16, 64, window), top_k=8),
            batcher=BatcherConfig(max_batch=window, max_wait_ms=3.0),
            overload=OverloadConfig(max_inflight=8 * window),
            placement=PlacementConfig(interval_s=3600.0, devices=n_dev,
                                      max_shard=2, cooldown_s=0.0),
        )
        app = MatchmakingApp(cfg)
        await app.start()
        rt = app.runtime("soak.hot")
        ctrl = app.placement
        reply_q = "soak.replies"
        app.broker.declare_queue(reply_q)
        matched: dict[str, int] = {}

        async def on_reply(delivery) -> None:
            d = json.loads(delivery.body)
            if d.get("status") == "matched":
                pid = str(d.get("player_id", ""))
                matched[pid] = matched.get(pid, 0) + 1

        app.broker.basic_consume(reply_q, on_reply, prefetch=1_000_000)

        rng = np.random.default_rng(int(args.placement_seed))
        rate = float(args.placement_rate)
        duration = float(args.placement_seconds)
        gap = 1.0 / max(1.0, rate)
        submitted = 0
        #: The scripted placement cycle, at fractions of the soak span.
        schedule = ([(0.2, ("migrate", (1,))),
                     (0.4, ("promote", (1, 2))),
                     (0.6, ("demote", (1,))),
                     (0.8, ("migrate", (0,)))]
                    if n_dev >= 3 else [(0.25, ("migrate", (1,))),
                                        (0.65, ("migrate", (0,)))])
        t0 = time.time()
        next_action = 0
        while time.time() - t0 < duration:
            frac = (time.time() - t0) / duration
            if next_action < len(schedule) and frac >= schedule[next_action][0]:
                kind, devices = schedule[next_action][1]
                next_action += 1
                await ctrl.force(kind, "soak.hot", devices,
                                 reason=f"soak script {kind}")
            burst = max(1, int(rate * 0.01))
            ratings = rng.normal(1500.0, 120.0, burst)
            for r in ratings:
                app.broker.publish(
                    "soak.hot",
                    f'{{"id":"s{submitted}","rating":{r:.2f}}}'.encode(),
                    Properties(reply_to=reply_q,
                               correlation_id=f"s{submitted}"))
                submitted += 1
            await asyncio.sleep(max(gap * burst, 0.001))
        # The cycle always completes: legs the load loop did not reach
        # (blackouts + a loaded box eat wall time) run now, against the
        # still-waiting pool — the blackout/lost/dup accounting must
        # cover the whole scripted cycle on every box speed.
        while next_action < len(schedule):
            kind, devices = schedule[next_action][1]
            next_action += 1
            await ctrl.force(kind, "soak.hot", devices,
                             reason=f"soak script {kind} (post-load)")
        # Drain: let in-flight work land.
        for _ in range(400):
            await asyncio.sleep(0.025)
            if (app.broker.queue_depth("soak.hot") == 0
                    and app.broker.queue_depth(reply_q) == 0
                    and app.broker.handlers_idle()
                    and rt.batcher.depth == 0 and rt._flushing == 0
                    and rt.engine.inflight() == 0):
                break
        waiting = {r.id for r in rt.engine.waiting()}
        dup = sum(n - 1 for n in matched.values() if n > 1)
        lost = submitted - len(matched) - len(waiting)
        snap = ctrl.snapshot()
        blackouts = [d["blackout_ms"] for d in snap["decisions"]
                     if d["status"] == "applied"]
        failed = [d for d in snap["decisions"]
                  if d["status"] in ("failed", "refused")]
        out = {
            "placement_soak_requests": submitted,
            "placement_soak_matched": len(matched),
            "placement_soak_waiting": len(waiting),
            "placement_migrations": len(blackouts),
            "placement_failed_actions": len(failed),
            "placement_blackout_ms_max": (round(max(blackouts), 3)
                                          if blackouts else None),
            "placement_blackout_ms_mean": (
                round(sum(blackouts) / len(blackouts), 3)
                if blackouts else None),
            "placement_lost": lost,
            "placement_dup": dup,
            "placement_devices": n_dev,
            "placement_final_binding": snap["live"]["soak.hot"]["devices"],
            "placement_decisions": snap["decisions"],
        }
        await app.stop()
        return out

    return asyncio.run(run())


def bench_crash_soak(args) -> dict:
    """Crash-restart soak (ISSUE 15, ``--crash-soak``): seeded load through
    N kill/recover cycles — each cycle boots a fresh app on the SAME
    journal directory, recovers the predecessor's hard-crash state,
    absorbs an at-least-once redelivery storm of every previous request,
    runs fresh deterministic load (designed pairs that match + singles
    that wait), and hard-crashes (``MatchmakingApp.crash()``: no drain, no
    clean marker, uncommitted buffers dropped). One cycle is a
    DEVICE-LOST cycle: a scripted ``ChaosConfig.device_lost`` fault mid-
    load demotes the D=2 sharded queue to its surviving device (measured
    blackout in the failover audit) before that cycle's crash.

    Emits ``crash_lost`` (waiting players missing after recovery),
    ``crash_dup`` (players seeing two distinct matches across the whole
    soak), ``crash_rto_ms_max/mean``, journal write amplification, the
    steady-state journal append overhead (fsync=window vs durability off
    at the same offered load), and — run twice — whether the recovery
    transcripts are bit-identical across runs. scripts/bench_diff.py
    gates the crash_* metrics direction-aware (lower is better)."""
    import asyncio
    import shutil
    import tempfile

    from matchmaking_tpu.config import (
        BatcherConfig,
        ChaosConfig,
        Config,
        DurabilityConfig,
        EngineConfig,
        QueueConfig,
    )
    from matchmaking_tpu.service.app import MatchmakingApp
    from matchmaking_tpu.service.broker import Properties

    q = "crash.soak"
    pairs = int(args.crash_pairs)
    singles = int(args.crash_singles)
    n_cycles = max(1, int(args.crash_cycles))
    dl_cycle = n_cycles - 1  # the last cycle loses a device mid-load

    def cfg_for(cycle: int | None, durable: bool = True,
                overhead: bool = False) -> Config:
        chaos = (ChaosConfig(seed=int(args.crash_seed), queues=(q,),
                             device_lost_steps=(1,))
                 if cycle == dl_cycle and cycle is not None
                 else ChaosConfig())
        return Config(
            queues=(QueueConfig(name=q, rating_threshold=50.0,
                                dedup_ttl_s=3600.0,
                                send_queued_ack=False),),
            engine=EngineConfig(backend="tpu", pool_capacity=4096,
                                pool_block=512, batch_buckets=(16, 64),
                                top_k=8, mesh_pool_axis=2,
                                # Pre-compile every bucket at app start:
                                # first-of-a-shape XLA compiles otherwise
                                # land inside whichever phase runs first
                                # (once mismeasured as ~95% "journal
                                # overhead") and inside the recovery span
                                # (the RTO must measure replay, not
                                # compilation).
                                warm_start=True),
            batcher=BatcherConfig(max_batch=64, max_wait_ms=2.0),
            # The soak cycles compact aggressively ON PURPOSE (snapshot
            # rotation is part of what each cycle must survive); the
            # OVERHEAD phase keeps the production budget so it measures
            # the steady-state append cost, not compaction churn.
            durability=(DurabilityConfig(
                journal_dir=args._crash_jdir, fsync="window",
                compact_records=(DurabilityConfig.compact_records
                                 if overhead
                                 else int(args.crash_compact_records)),
                compact_interval_s=0.1) if durable
                else DurabilityConfig()),
            chaos=chaos,
        )

    def cycle_load(cycle: int) -> "list[tuple[str, float]]":
        """Deterministic designed load: pairs at adjacent ratings (they
        MUST match, whatever the window composition) + far-apart singles
        (they can never match anything, this cycle or later) — the
        matched/waiting SETS are pure functions of the script, which is
        what makes the recovery transcript two-run bit-identical."""
        rows: list[tuple[str, float]] = []
        for i in range(pairs):
            base = 1000.0 + i * 200.0
            rows.append((f"c{cycle}p{2 * i}", base))
            rows.append((f"c{cycle}p{2 * i + 1}", base + 1.0))
        for i in range(singles):
            rows.append((f"c{cycle}s{i}", 50_000.0 + cycle * 10_000.0
                         + i * 1_000.0))
        # The contract rejects |rating| >= 1e5 at the middleware — a
        # single pushed past it would be silently dropped instead of
        # waiting, corrupting the lost/dup accounting. Refuse loudly.
        worst = max(r for _, r in rows)
        if worst >= 1e5:
            raise ValueError(
                f"--crash-cycles/--crash-singles too large: cycle {cycle} "
                f"would publish rating {worst} >= the contract bound 1e5 "
                f"(singles climb 10k per cycle from 50k)")
        # Seeded publish-order shuffle: the soak is order-insensitive by
        # design, and the shuffle proves it stays that way.
        rng = np.random.default_rng(int(args.crash_seed) + cycle)
        rng.shuffle(rows)
        return rows

    async def quiesce(app, rt, matched_at_least: int) -> bool:
        # 5 ms poll: the overhead phase's measured span ends here, and a
        # coarser tick would quantize the rate it feeds (the soak cycles
        # share the helper and are insensitive to it).
        from matchmaking_tpu.testing.drain import fully_drained
        for _ in range(6000):
            await asyncio.sleep(0.005)
            if fully_drained(app, rt, q, matched_at_least):
                return True
        return False

    async def one_run(run_idx: int) -> dict:
        jdir = tempfile.mkdtemp(prefix=f"mm_crash_soak_r{run_idx}_")
        args._crash_jdir = jdir
        lost = 0
        rtos: list[float] = []
        transcripts: list[dict] = []
        match_of: dict[str, set[str]] = {}
        pre_waiting: set[str] = set()
        prev_rows: list[tuple[str, float]] = []
        write_amp = None
        failovers = 0
        failover_blackout_ms = None
        try:
            for cycle in range(n_cycles):
                app = MatchmakingApp(cfg_for(cycle))
                await app.start()
                rt = app.runtime(q)
                # Recovery accounting vs the pre-crash truth.
                recovered = {r.id for r in rt.engine.waiting()}
                lost += len(pre_waiting - recovered)
                if cycle > 0:
                    rto = app.metrics.gauges.get(f"crash_rto_ms[{q}]")
                    if rto is not None:
                        rtos.append(float(rto))
                    if rt.last_recovery is not None:
                        transcripts.append(
                            rt.last_recovery["transcript"])
                reply_q = f"crash.replies.{cycle}"
                app.broker.declare_queue(reply_q)

                async def on_reply(delivery) -> None:
                    d = json.loads(delivery.body)
                    if d.get("status") == "matched":
                        pid = str(d.get("player_id", ""))
                        mid = (d.get("match") or {}).get("match_id")
                        if pid and mid:
                            match_of.setdefault(pid, set()).add(mid)

                app.broker.basic_consume(reply_q, on_reply,
                                         prefetch=1_000_000)
                # At-least-once redelivery storm: EVERY previous-cycle
                # request again. Matched players must replay their cached
                # match (same id → no dup); waiting singles re-enter as
                # duplicate-enqueue no-ops.
                for pid, rating in prev_rows:
                    app.broker.publish(
                        q, f'{{"id":"{pid}","rating":{rating}}}'.encode(),
                        Properties(reply_to=reply_q, correlation_id=pid))
                # Fresh seeded load, paced so the batcher cuts several
                # windows (the device-lost cycle needs step index 1 to
                # exist mid-load, not after it).
                rows = cycle_load(cycle)
                gap = 1.0 / max(1.0, float(args.crash_rate))
                for k, (pid, rating) in enumerate(rows):
                    app.broker.publish(
                        q, f'{{"id":"{pid}","rating":{rating}}}'.encode(),
                        Properties(reply_to=reply_q, correlation_id=pid))
                    if k % 4 == 3:
                        await asyncio.sleep(gap * 4)
                ok = await quiesce(app, rt, matched_at_least=2 * pairs)
                if not ok:
                    log(f"[crash-soak r{run_idx} c{cycle}] WARNING: "
                        f"quiesce timed out")
                if cycle == dl_cycle:
                    failovers += int(
                        app.metrics.counters.get("device_failovers"))
                    if rt.failover_log:
                        failover_blackout_ms = (
                            rt.failover_log[-1]["blackout_ms"])
                if rt.journal is not None and rt.journal.payload_bytes:
                    write_amp = round(rt.journal.bytes_written
                                      / rt.journal.payload_bytes, 3)
                pre_waiting = {r.id for r in rt.engine.waiting()}
                prev_rows = rows
                log(f"[crash-soak r{run_idx} c{cycle}] matched="
                    f"{app.metrics.counters.get('players_matched')} "
                    f"waiting={len(pre_waiting)} "
                    f"replays="
                    f"{app.metrics.counters.get('deduped_replays')}")
                await app.crash()
            # Final recovery check: one more boot proves the LAST crash
            # recovers too, then stops cleanly.
            app = MatchmakingApp(cfg_for(None))
            await app.start()
            rt = app.runtime(q)
            recovered = {r.id for r in rt.engine.waiting()}
            lost += len(pre_waiting - recovered)
            rto = app.metrics.gauges.get(f"crash_rto_ms[{q}]")
            if rto is not None:
                rtos.append(float(rto))
            if rt.last_recovery is not None:
                transcripts.append(rt.last_recovery["transcript"])
            await app.stop()
        finally:
            if not args.crash_keep_dirs:
                shutil.rmtree(jdir, ignore_errors=True)
        dup = sum(1 for ids in match_of.values() if len(ids) > 1)
        return {
            "lost": lost,
            "dup": dup,
            "rtos": rtos,
            "transcripts": transcripts,
            "matched_players": len(match_of),
            "write_amplification": write_amp,
            "failovers": failovers,
            "failover_blackout_ms": failover_blackout_ms,
        }

    async def rate_phase(durable: bool) -> "tuple[float, bool]":
        """Steady-state append-overhead measurement: the same designed
        paired load through a durability-on (fsync=window) vs -off app;
        the ratio of matched-players rates is the overhead. Returns
        ``(rate, drained)`` — a quiesce that times out folds up to 30 s
        of idle polling into the measured span, so the caller must treat
        the rate (and the overhead fraction built from it) as garbage
        rather than gate on it."""
        n = int(args.crash_overhead_pairs)
        args._crash_jdir = tempfile.mkdtemp(prefix="mm_crash_ovh_")
        try:
            app = MatchmakingApp(cfg_for(None, durable=durable,
                                         overhead=True))
            await app.start()
            rt = app.runtime(q)
            # Warm EVERY batch bucket outside the measured span: the
            # first cut of each window SHAPE pays its XLA compile, and
            # the compile cache is process-wide — the 2-player warmup
            # alone left the 64-bucket compile inside whichever phase ran
            # FIRST, which once mismeasured as ~95% "journal overhead".
            # A full-burst publish of max_batch pairs cuts one max-size
            # window and the remainder buckets; pairs at far-apart bases
            # all match and leave the pool before t0.
            # Base 80k: far from the measured load (≤ ~4.6k) but INSIDE
            # the contract's rating bound (|r| < 1e5 — a 100k base was
            # silently rejected_by_middleware wholesale, which unwarmed
            # the phase and left the compiles in the measured span).
            warm_pairs = 64
            for i in range(warm_pairs):
                base = 80_000.0 + i * 200.0
                for jj, r in enumerate((base, base + 1.0)):
                    app.broker.publish(
                        q,
                        f'{{"id":"w{2 * i + jj}","rating":{r}}}'.encode(),
                        Properties(reply_to="", correlation_id=""))
            warm_ok = await quiesce(app, rt, matched_at_least=2 * warm_pairs)
            matched0 = app.metrics.counters.get("players_matched")
            t0 = time.perf_counter()
            # Burst-published: the broker drains full bursts, so windows
            # fill to max_batch and the journal pays its one buffered
            # append + one fsync PER WINDOW — the steady-state shape.
            for i in range(n):
                base = 1000.0 + (i % 512) * 7.0
                for j, r in enumerate((base, base + 1.0)):
                    app.broker.publish(
                        q,
                        f'{{"id":"o{2 * i + j}","rating":{r}}}'.encode(),
                        Properties(reply_to="", correlation_id=""))
            ok = await quiesce(app, rt, matched_at_least=matched0 + 2 * n)
            span = time.perf_counter() - t0
            matched = app.metrics.counters.get("players_matched") - matched0
            await app.stop()
            if not (warm_ok and ok):
                log(f"[crash-soak overhead durable={durable}] WARNING: "
                    f"quiesce timed out (warm={warm_ok}, measured={ok}) — "
                    f"the span includes idle drain polling, overhead "
                    f"fraction withheld")
            return (matched / span if span > 0 else 0.0, warm_ok and ok)
        finally:
            shutil.rmtree(args._crash_jdir, ignore_errors=True)

    runs = [asyncio.run(one_run(i))
            for i in range(max(1, int(args.crash_runs)))]
    rate_on, on_ok = asyncio.run(rate_phase(True))
    rate_off, off_ok = asyncio.run(rate_phase(False))
    # A timed-out quiesce poisons the rate it measured: report the rates
    # (flagged in the log) but withhold the gated overhead fraction —
    # bench_diff skips None rather than flagging a phantom regression.
    overhead = (max(0.0, 1.0 - rate_on / rate_off)
                if rate_off > 0 and on_ok and off_ok else None)
    first = runs[0]
    identical = None
    if len(runs) >= 2:
        identical = all(
            json.dumps(r["transcripts"], sort_keys=True)
            == json.dumps(first["transcripts"], sort_keys=True)
            for r in runs[1:])
    rtos = [x for r in runs for x in r["rtos"]]
    return {
        "crash_cycles": n_cycles,
        "crash_runs": len(runs),
        "crash_lost": sum(r["lost"] for r in runs),
        "crash_dup": sum(r["dup"] for r in runs),
        "crash_rto_ms_max": round(max(rtos), 3) if rtos else None,
        "crash_rto_ms_mean": (round(sum(rtos) / len(rtos), 3)
                              if rtos else None),
        "crash_recoveries": len(rtos),
        "crash_matched_players": first["matched_players"],
        "crash_transcript_identical": identical,
        "crash_device_failovers": sum(r["failovers"] for r in runs),
        "crash_failover_blackout_ms": first["failover_blackout_ms"],
        "journal_write_amplification": first["write_amplification"],
        "crash_e2e_rate_on": round(rate_on, 1),
        "crash_e2e_rate_off": round(rate_off, 1),
        "crash_journal_overhead_frac": (round(overhead, 4)
                                        if overhead is not None else None),
    }


def bench_failover_soak(args) -> dict:
    """Failover soak (ISSUE 17, ``--failover-soak``): seeded load through
    N primary-kill/standby-takeover cycles on the CPU harness. Each cycle
    boots the current owner's app on a FRESH journal dir (a new "host"),
    attaches a warm standby over the in-process replication link, runs
    deterministic designed load (pairs that match + singles that wait)
    with the standby pumping, hard-kills the primary
    (``MatchmakingApp.crash()``), expires the lease on the authority's
    scriptable clock, promotes the standby (epoch bump = fencing), and
    boots the successor, which adopts the standby's shadow
    (``recover_from_replica`` — the measured ``failover_rto_ms``).

    Chaos: cycle 0 runs a scripted drop/dup/delay vocabulary on the
    stream's first seqs (retransmission must heal them — zero loss); the
    LAST cycle partitions the link at a quiesced seq boundary and
    publishes late singles behind the cut, so the kill lands with real
    replication lag — the lost players must stay ``<=`` the
    ``unacked_admit_players()`` bound measured at kill time, and the cut
    at a quiesced boundary keeps the lost SET framing-independent (the
    two-run transcript gate needs that).

    Emits ``failover_lost`` / ``failover_dup`` / ``failover_rto_ms`` /
    ``replication_lag_ms_p99`` (gated by scripts/bench_diff.py, lower is
    better; lost/dup under the zero-baseline rule) plus the lost bound,
    recovery count, and the two-run transcript identity pin.

    ``--transport`` (ISSUE 20): ``socket-loopback`` runs THIS script
    unchanged over real UDS sockets + a remote lease client (nemesis
    off) — the in-proc ≡ socket equivalence pin: the emitted
    ``failover_transcript_digest`` must be bit-identical to an inproc
    run on the same seed. ``socket`` dispatches to the cross-process
    soak (:func:`bench_failover_soak_proc`)."""
    import asyncio
    import hashlib
    import shutil
    import tempfile

    from matchmaking_tpu.config import (
        BatcherConfig,
        ChaosConfig,
        Config,
        DurabilityConfig,
        EngineConfig,
        QueueConfig,
        ReplicationConfig,
    )
    from matchmaking_tpu.service.app import MatchmakingApp
    from matchmaking_tpu.service.broker import Properties
    from matchmaking_tpu.service.replication import ReplicationHub

    transport = getattr(args, "transport", "inproc")
    if transport == "socket":
        return bench_failover_soak_proc(args)

    q = "failover.soak"
    pairs = int(args.failover_pairs)
    singles = int(args.failover_singles)
    late_singles = int(args.failover_late_singles)
    n_cycles = max(1, int(args.failover_cycles))
    lag_cycle = n_cycles - 1  # the last kill lands with replication lag
    lease_s = float(args.failover_lease_s)
    loopback = transport == "socket-loopback"
    if loopback:
        # Real renewals ride the remote client's budgeted validity:
        # floor the lease so an XLA warm-up stall on the CPU harness
        # can't lapse it mid-boot. Transcripts are recovered-state
        # functions — lease duration never enters them, so the
        # equivalence pin against inproc (default lease) still holds.
        lease_s = max(lease_s, 2.0)

    def make_hub(chaos):
        if loopback:
            from matchmaking_tpu.net.link import SocketReplicationHub

            return SocketReplicationHub(lease_s=lease_s, chaos=chaos,
                                        seed=int(args.failover_seed))
        return ReplicationHub(lease_s=lease_s, chaos=chaos,
                              seed=int(args.failover_seed))

    def cfg_for(jdir: str, owner: str) -> Config:
        return Config(
            queues=(QueueConfig(name=q, rating_threshold=50.0,
                                dedup_ttl_s=3600.0,
                                send_queued_ack=False),),
            engine=EngineConfig(backend="tpu", pool_capacity=4096,
                                pool_block=512, batch_buckets=(16, 64),
                                top_k=8,
                                # warm_start: XLA compiles must land
                                # before the load, not inside the
                                # measured failover RTO.
                                warm_start=True),
            batcher=BatcherConfig(max_batch=64, max_wait_ms=2.0),
            durability=DurabilityConfig(journal_dir=jdir, fsync="window"),
            replication=ReplicationConfig(role="primary", owner=owner),
        )

    def cycle_load(cycle: int) -> "list[tuple[str, float]]":
        """Designed load (the crash-soak recipe): adjacent-rating pairs
        MUST match whatever the window framing; far singles never can —
        the matched/waiting SETS are pure functions of the script."""
        rows: list[tuple[str, float]] = []
        for i in range(pairs):
            base = 1000.0 + i * 200.0
            rows.append((f"f{cycle}p{2 * i}", base))
            rows.append((f"f{cycle}p{2 * i + 1}", base + 1.0))
        for i in range(singles):
            rows.append((f"f{cycle}s{i}", 50_000.0 + cycle * 10_000.0
                         + i * 1_000.0))
        worst = max(r for _, r in rows)
        if worst >= 1e5:
            raise ValueError(
                f"--failover-cycles/--failover-singles too large: cycle "
                f"{cycle} would publish rating {worst} >= the contract "
                f"bound 1e5")
        rng = np.random.default_rng(int(args.failover_seed) + cycle)
        rng.shuffle(rows)
        return rows

    async def quiesce(app, rt, standby, matched_at_least: int,
                      replication: bool = True) -> bool:
        from matchmaking_tpu.testing.drain import fully_drained
        for _ in range(6000):
            await asyncio.sleep(0.005)
            if standby is not None:
                standby.pump()
            if fully_drained(app, rt, q, matched_at_least,
                             replication=replication):
                return True
        return False

    async def one_run(run_idx: int) -> dict:
        base_dir = tempfile.mkdtemp(prefix=f"mm_failover_r{run_idx}_")
        # One replication fabric per run: the lease authority, the
        # per-queue link, and the takeover handoff all survive the app
        # boots (they model the parts of the deployment that OUTLIVE a
        # host). Cycle 0's scripted drop/dup/delay seqs exercise the
        # at-least-once retransmission path; they must heal to zero loss.
        chaos = ChaosConfig(seed=int(args.failover_seed), queues=(q,),
                            repl_drop_seqs=(1,), repl_dup_seqs=(2,),
                            repl_delay_seqs=((3, 2),))
        # The repl_* script above is the IN-PROC link's vocabulary; the
        # loopback socket link ignores it (its faults are net_*, off
        # here) — the in-proc faults heal to zero effect by the quiesce
        # boundaries, which is exactly why the transcripts stay
        # bit-identical across transports.
        hub = make_hub(chaos)
        lost = 0
        lost_bound = 0
        over_bound = 0
        rtos: list[float] = []
        lag_p99s: list[float] = []
        transcripts: list[dict] = []
        match_of: dict[str, set[str]] = {}
        pre_waiting: set[str] = set()
        kill_bound = 0
        prev_rows: list[tuple[str, float]] = []
        owner = "host0"
        try:
            for cycle in range(n_cycles):
                if hasattr(hub, "cycle_reset"):
                    # Socket fabric: retire the previous host
                    # generation's link + standby listener so the fresh
                    # journal's restarted seqs aren't shadowed by the
                    # old cumulative ack watermark.
                    hub.cycle_reset(q)
                app = MatchmakingApp(
                    cfg_for(f"{base_dir}/host{cycle}", owner),
                    replication_hub=hub)
                await app.start()
                rt = app.runtime(q)
                recovered = {r.id for r in rt.engine.waiting()}
                cycle_lost = len(pre_waiting - recovered)
                lost += cycle_lost
                lost_bound += kill_bound
                over_bound += max(0, cycle_lost - kill_bound)
                if cycle_lost > kill_bound:
                    log(f"[failover-soak r{run_idx} c{cycle}] GATE: lost "
                        f"{cycle_lost} players but the unacked-tail bound "
                        f"at kill time was {kill_bound}")
                if cycle > 0:
                    rto = app.metrics.gauges.get(f"failover_rto_ms[{q}]")
                    if rto is not None:
                        rtos.append(float(rto))
                    if rt.last_recovery is not None:
                        transcripts.append(rt.last_recovery["transcript"])
                reply_q = f"failover.replies.{cycle}"
                app.broker.declare_queue(reply_q)

                async def on_reply(delivery) -> None:
                    d = json.loads(delivery.body)
                    if d.get("status") == "matched":
                        pid = str(d.get("player_id", ""))
                        mid = (d.get("match") or {}).get("match_id")
                        if pid and mid:
                            match_of.setdefault(pid, set()).add(mid)

                app.broker.basic_consume(reply_q, on_reply,
                                         prefetch=1_000_000)
                # The NEXT host's warm standby attaches before the load:
                # it receives the baseline plus every streamed record.
                standby = hub.standby(q, owner=f"host{cycle + 1}")
                # At-least-once redelivery storm of every previous-cycle
                # request: matched players must replay their cached match
                # (the dedup cache crossed hosts via the stream).
                for pid, rating in prev_rows:
                    app.broker.publish(
                        q, f'{{"id":"{pid}","rating":{rating}}}'.encode(),
                        Properties(reply_to=reply_q, correlation_id=pid))
                rows = cycle_load(cycle)
                gap = 1.0 / max(1.0, float(args.failover_rate))
                for k, (pid, rating) in enumerate(rows):
                    app.broker.publish(
                        q, f'{{"id":"{pid}","rating":{rating}}}'.encode(),
                        Properties(reply_to=reply_q, correlation_id=pid))
                    if k % 4 == 3:
                        await asyncio.sleep(gap * 4)
                ok = await quiesce(app, rt, standby,
                                   matched_at_least=2 * pairs)
                if not ok:
                    log(f"[failover-soak r{run_idx} c{cycle}] WARNING: "
                        f"quiesce timed out")
                if cycle == lag_cycle and late_singles > 0:
                    # Kill under lag: cut the link at the quiesced seq
                    # boundary (acked == sent here, so the held tail is
                    # exactly the late load — framing-independent), then
                    # publish singles the standby will never see.
                    repl = rt.replication
                    hub.link(q).partition(repl.sent_seq + 1)
                    for i in range(late_singles):
                        pid = f"f{cycle}L{i}"
                        app.broker.publish(
                            q,
                            f'{{"id":"{pid}","rating":{90_000.0 + i * 1_000.0}}}'
                            .encode(),
                            Properties(reply_to=reply_q,
                                       correlation_id=pid))
                    ok = await quiesce(app, rt, standby,
                                       matched_at_least=2 * pairs,
                                       replication=False)
                    if not ok:
                        log(f"[failover-soak r{run_idx} c{cycle}] "
                            f"WARNING: lag-cycle quiesce timed out")
                repl = rt.replication
                kill_bound = repl.unacked_admit_players()
                lat = app.metrics.latency.get(
                    f"replication_ack_lag[{q}]")
                if lat is not None and len(lat):
                    lag_p99s.append(lat.percentile(99) * 1e3)
                pre_waiting = {r.id for r in rt.engine.waiting()}
                prev_rows = rows
                log(f"[failover-soak r{run_idx} c{cycle}] matched="
                    f"{app.metrics.counters.get('players_matched')} "
                    f"waiting={len(pre_waiting)} lag={repl.lag()} "
                    f"bound={kill_bound} epoch={repl.epoch}")
                await app.crash()
                # Takeover after lease expiry on the authority's
                # scriptable clock (time is a caller-passed monotonic
                # value by design — no wall-clock sleep needed).
                standby.takeover(time.monotonic() + lease_s + 0.05)
                owner = standby.owner
            # Final successor: the last takeover must adopt too, then
            # stop cleanly (CLEAN record + lease release).
            if hasattr(hub, "cycle_reset"):
                hub.cycle_reset(q)
            app = MatchmakingApp(
                cfg_for(f"{base_dir}/host{n_cycles}", owner),
                replication_hub=hub)
            await app.start()
            rt = app.runtime(q)
            recovered = {r.id for r in rt.engine.waiting()}
            cycle_lost = len(pre_waiting - recovered)
            lost += cycle_lost
            lost_bound += kill_bound
            over_bound += max(0, cycle_lost - kill_bound)
            if cycle_lost > kill_bound:
                log(f"[failover-soak r{run_idx} final] GATE: lost "
                    f"{cycle_lost} players but the unacked-tail bound "
                    f"at kill time was {kill_bound}")
            rto = app.metrics.gauges.get(f"failover_rto_ms[{q}]")
            if rto is not None:
                rtos.append(float(rto))
            if rt.last_recovery is not None:
                transcripts.append(rt.last_recovery["transcript"])
            await app.stop()
        finally:
            if hasattr(hub, "close"):
                hub.close()
            if not args.failover_keep_dirs:
                shutil.rmtree(base_dir, ignore_errors=True)
        dup = sum(1 for ids in match_of.values() if len(ids) > 1)
        return {
            "lost": lost,
            "lost_bound": lost_bound,
            "over_bound": over_bound,
            "dup": dup,
            "rtos": rtos,
            "lag_p99s": lag_p99s,
            "transcripts": transcripts,
            "matched_players": len(match_of),
        }

    runs = [asyncio.run(one_run(i))
            for i in range(max(1, int(args.failover_runs)))]
    first = runs[0]
    identical = None
    if len(runs) >= 2:
        identical = all(
            json.dumps(r["transcripts"], sort_keys=True)
            == json.dumps(first["transcripts"], sort_keys=True)
            for r in runs[1:])
    rtos = [x for r in runs for x in r["rtos"]]
    lags = [x for r in runs for x in r["lag_p99s"]]
    digest = hashlib.sha256(
        json.dumps(first["transcripts"], sort_keys=True).encode()
    ).hexdigest()
    return {
        "failover_transport": transport,
        # The equivalence pin: an inproc run and a socket-loopback run
        # on the same seed must emit the SAME digest (check.sh compares).
        "failover_transcript_digest": digest,
        "failover_cycles": n_cycles,
        "failover_runs": len(runs),
        "failover_lost": sum(r["lost"] for r in runs),
        "failover_lost_bound": sum(r["lost_bound"] for r in runs),
        "failover_lost_over_bound": sum(r["over_bound"] for r in runs),
        "failover_dup": sum(r["dup"] for r in runs),
        "failover_rto_ms": round(max(rtos), 3) if rtos else None,
        "failover_rto_ms_mean": (round(sum(rtos) / len(rtos), 3)
                                 if rtos else None),
        "failover_recoveries": len(rtos),
        "failover_matched_players": first["matched_players"],
        "failover_transcript_identical": identical,
        "replication_lag_ms_p99": (round(max(lags), 3) if lags else None),
    }


def bench_failover_soak_proc(args) -> dict:
    """CROSS-PROCESS failover soak (ISSUE 20, ``--failover-soak
    --transport=socket``): the PR 17 invariants gated over real process
    and socket boundaries. The driver spawns a lease-service subprocess
    (the part of the deployment that outlives every host) and a chain of
    host subprocesses (``net/failover_proc.py``); each host attaches as
    the warm standby of the current primary over a UDS replication
    stream + remote lease RPCs, then the driver SIGKILLs the primary
    mid-tenure and the standby takes over after REAL lease expiry.

    Nemesis schedule: cycle 0's primary runs a scripted net fault script
    (drop + dup + delay + one MID-STREAM CONNECTION RESET on the fwd
    flow — the link must reconnect and converge by retransmission); the
    middle cycles are clean (any ``liveness_lost`` there is a heartbeat
    FALSE POSITIVE — zero-gated); the LAST cycle arms an ASYMMETRIC
    partition before the kill (the primary keeps streaming but goes deaf
    to acks and lease responses), so the driver can prove the primary
    SELF-FENCES within the lease budget — both seams probed refused —
    while the standby still catches up on the working direction.

    Gates (all emitted, zero-baseline in scripts/bench_diff.py): zero
    double matches across the merged per-host reply logs, losses <= the
    unacked-tail bound at each kill, fenced probes refused at both
    seams, >= 1 link reconnect after the scripted reset, zero heartbeat
    false positives in clean tenures, and two seeded runs bit-identical
    by takeover-transcript digest."""
    import hashlib
    import os
    import queue as queue_mod
    import shutil
    import subprocess
    import tempfile
    import threading

    q = "failover.soak"
    pairs = int(args.failover_pairs)
    singles = int(args.failover_singles)
    late_singles = int(args.failover_late_singles)
    n_cycles = max(2, int(args.failover_cycles))
    seed = int(args.failover_seed)
    rate = float(args.failover_rate)
    # Real clocks across processes: floor the lease above the worst XLA
    # warm-up stall so a compiling primary can't lapse it spuriously.
    lease_s = max(float(args.failover_lease_s), 2.0)

    def cycle_load(cycle: int) -> "list[list[Any]]":
        rows: "list[list[Any]]" = []
        for i in range(pairs):
            base = 1000.0 + i * 200.0
            rows.append([f"f{cycle}p{2 * i}", base])
            rows.append([f"f{cycle}p{2 * i + 1}", base + 1.0])
        for i in range(singles):
            rows.append([f"f{cycle}s{i}", 50_000.0 + cycle * 10_000.0
                         + i * 1_000.0])
        rng = np.random.default_rng(seed + cycle)
        rng.shuffle(rows)
        return rows

    class Child:
        """One subprocess + its JSON-line protocol (stdin commands,
        stdout events; a reader thread feeds a local queue)."""

        def __init__(self, name: str, argv: "list[str]"):
            self.name = name
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "matchmaking_tpu.net.failover_proc",
                 *argv],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            self.events: "queue_mod.Queue" = queue_mod.Queue()
            self._rid = 0
            threading.Thread(target=self._read, daemon=True).start()

        def _read(self) -> None:
            assert self.proc.stdout is not None
            for line in self.proc.stdout:
                line = line.strip()
                if line:
                    try:
                        self.events.put(json.loads(line))
                    except ValueError:
                        pass
            self.events.put(None)

        def _next(self, deadline: float) -> "dict | None":
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"{self.name}: protocol timeout")
            try:
                return self.events.get(timeout=remaining)
            except queue_mod.Empty:
                raise TimeoutError(f"{self.name}: protocol timeout")

        def wait_ev(self, ev: str, timeout: float = 120.0) -> dict:
            deadline = time.monotonic() + timeout
            while True:
                got = self._next(deadline)
                if got is None:
                    raise RuntimeError(
                        f"{self.name}: exited before {ev!r}")
                if got.get("ev") == ev:
                    return got

        def rpc(self, cmd: str, timeout: float = 120.0, **kw) -> dict:
            self._rid += 1
            assert self.proc.stdin is not None
            self.proc.stdin.write(
                json.dumps({"cmd": cmd, "id": self._rid, **kw}) + "\n")
            self.proc.stdin.flush()
            deadline = time.monotonic() + timeout
            while True:
                got = self._next(deadline)
                if got is None:
                    raise RuntimeError(f"{self.name}: died during {cmd!r}")
                if got.get("id") != self._rid:
                    continue
                if got.get("ev") == "error":
                    raise RuntimeError(
                        f"{self.name}: {cmd} failed: {got.get('error')}")
                return got

        def kill(self) -> None:
            self.proc.kill()  # SIGKILL — the crash under test

        def reap(self) -> None:
            try:
                self.proc.kill()
            except OSError:
                pass
            try:
                self.proc.wait(timeout=10)
            except Exception:
                pass

    def merge_match_of(into: "dict[str, set]", rep: dict) -> None:
        for pid, mids in (rep.get("match_of") or {}).items():
            into.setdefault(pid, set()).update(mids)

    def one_run(run_idx: int) -> dict:
        base = tempfile.mkdtemp(prefix=f"mm_netfo_r{run_idx}_")
        lease_addr = f"unix:{base}/lease.sock"
        fwd = f"repl:{q}:fwd"
        # Cycle 0's nemesis script: first-tx drop/dup/delay on early
        # record seqs plus a MID-STREAM reset — heals by reconnect +
        # retransmission, gated by link_reconnects >= 1 and zero loss.
        c0_chaos = json.dumps({
            "seed": seed, "queues": [q],
            "net_drop_frames": [[fwd, 2]], "net_dup_frames": [[fwd, 3]],
            "net_delay_frames": [[fwd, 4, 2]],
            "net_reset_frames": [[fwd, 6]]})

        def spawn_host(idx: int, chaos: "str | None" = None) -> Child:
            argv = ["host", "--name", f"host{idx}", "--queue", q,
                    "--lease-addr", lease_addr, "--lease-s", str(lease_s),
                    "--seed", str(seed)]
            if chaos:
                argv += ["--chaos", chaos]
            c = Child(f"host{idx}", argv)
            c.wait_ev("ready", timeout=180.0)
            return c

        children: "list[Child]" = []
        lease = Child("lease", ["lease", "--lease-addr", lease_addr,
                                "--lease-s", str(lease_s)])
        res = {"lost": 0, "lost_bound": 0, "over_bound": 0,
               "reconnects": 0, "hb_false_positives": 0,
               "fenced_probe_failures": 0, "rtos": [], "transcripts": []}
        match_of: "dict[str, set]" = {}
        try:
            lease.wait_ev("ready", timeout=180.0)
            primary = spawn_host(0, chaos=c0_chaos)
            children.append(primary)
            standby = spawn_host(1)
            children.append(standby)
            standby.rpc("standby", listen=f"unix:{base}/repl1.sock")
            primary.rpc("serve", target=f"unix:{base}/repl1.sock",
                        jdir=f"{base}/host0", timeout=300.0)
            prev_rows: "list[list[Any]]" = []
            for cycle in range(n_cycles):
                if prev_rows:
                    # At-least-once redelivery storm: matched players
                    # must replay their cached match on the NEW host.
                    primary.rpc("publish", rows=prev_rows, rate=rate)
                rows = cycle_load(cycle)
                primary.rpc("publish", rows=rows, rate=rate)
                qq = primary.rpc("quiesce", matched_at_least=2 * pairs,
                                 replication=True, timeout_s=60.0,
                                 timeout=90.0)
                if not qq.get("ok"):
                    log(f"[netfo r{run_idx} c{cycle}] WARNING: quiesce "
                        f"timed out")
                asym = cycle == n_cycles - 1 and late_singles > 0
                if asym:
                    # Asymmetric partition: the primary keeps streaming
                    # but goes DEAF to acks and lease responses.
                    primary.rpc("deafen", pattern=f"repl:{q}:ack")
                    primary.rpc("deafen", pattern="lease:")
                    late_rows = [[f"f{cycle}L{i}", 90_000.0 + i * 1_000.0]
                                 for i in range(late_singles)]
                    primary.rpc("publish", rows=late_rows, rate=rate)
                    primary.rpc("quiesce", matched_at_least=2 * pairs,
                                replication=False, timeout_s=30.0,
                                timeout=60.0)
                    # The fwd direction still works: the standby must
                    # catch up even while the primary sees no acks.
                    prep = primary.rpc("report")
                    deadline = time.monotonic() + 30.0
                    while True:
                        srep = standby.rpc("report")
                        if (srep.get("applied_seq", 0)
                                >= prep.get("sent_seq", 0)):
                            break
                        if time.monotonic() > deadline:
                            log(f"[netfo r{run_idx}] WARNING: standby "
                                f"never caught up under asym partition")
                            break
                        time.sleep(0.05)
                    # Fencing-over-RTT: with renewals unconfirmable the
                    # primary must fence ITSELF within the lease budget
                    # — both seams probed, refusal required.
                    probe = primary.rpc("probe",
                                        timeout_s=4 * lease_s + 10.0,
                                        timeout=4 * lease_s + 30.0)
                    if not (probe.get("publish_refused")
                            and probe.get("append_fenced")
                            and not probe.get("publish_leaked")):
                        res["fenced_probe_failures"] += 1
                        log(f"[netfo r{run_idx}] GATE: fenced probe "
                            f"leaked: {probe}")
                rep = primary.rpc("report")
                kill_bound = int(rep.get("kill_bound", 0))
                pre_waiting = set(rep.get("waiting", ()))
                merge_match_of(match_of, rep)
                link = rep.get("link", {})
                res["reconnects"] += int(link.get("reconnects", 0))
                if cycle != 0 and not asym:
                    # Clean tenure: any liveness_lost is a heartbeat
                    # FALSE POSITIVE (zero-gated).
                    srep = standby.rpc("report")
                    res["hb_false_positives"] += (
                        int(link.get("liveness_lost", 0))
                        + int(srep.get("standby_link", {})
                              .get("liveness_lost", 0)))
                log(f"[netfo r{run_idx} c{cycle}] matched="
                    f"{rep.get('matched')} waiting={len(pre_waiting)} "
                    f"bound={kill_bound} epoch={rep.get('epoch')} "
                    f"reconnects={link.get('reconnects', 0)}")
                primary.kill()
                to = standby.rpc("takeover", timeout_s=4 * lease_s + 30.0,
                                 timeout=4 * lease_s + 60.0)
                if cycle < n_cycles - 1:
                    nxt = spawn_host(cycle + 2)
                    children.append(nxt)
                    nxt.rpc("standby",
                            listen=f"unix:{base}/repl{cycle + 2}.sock")
                    target = f"unix:{base}/repl{cycle + 2}.sock"
                else:
                    # Last successor streams to a dead-end address (no
                    # listener will ever bind it) and must still stop
                    # cleanly: a missing standby degrades, never wedges.
                    target = f"unix:{base}/deadend.sock"
                sv = standby.rpc("serve", target=target,
                                 jdir=f"{base}/host{cycle + 1}",
                                 timeout=300.0)
                recovered = set(sv.get("recovered", ()))
                cycle_lost = len(pre_waiting - recovered)
                res["lost"] += cycle_lost
                res["lost_bound"] += kill_bound
                res["over_bound"] += max(0, cycle_lost - kill_bound)
                if cycle_lost > kill_bound:
                    log(f"[netfo r{run_idx} c{cycle}] GATE: lost "
                        f"{cycle_lost} > unacked-tail bound {kill_bound}")
                if sv.get("rto_ms") is not None:
                    res["rtos"].append(float(sv["rto_ms"]))
                if sv.get("transcript") is not None:
                    res["transcripts"].append(sv["transcript"])
                log(f"[netfo r{run_idx} c{cycle}] takeover epoch="
                    f"{to.get('epoch')} lost={cycle_lost} "
                    f"rto_ms={sv.get('rto_ms')}")
                primary, standby = standby, None
                if cycle < n_cycles - 1:
                    standby = children[-1]
                prev_rows = rows
            frep = primary.rpc("report")
            merge_match_of(match_of, frep)
            primary.rpc("stop", timeout=120.0)
        finally:
            for c in children:
                c.reap()
            lease.reap()
            if not args.failover_keep_dirs:
                shutil.rmtree(base, ignore_errors=True)
        res["dup"] = sum(1 for ids in match_of.values() if len(ids) > 1)
        res["matched_players"] = len(match_of)
        res["digest"] = hashlib.sha256(
            json.dumps(res["transcripts"], sort_keys=True).encode()
        ).hexdigest()
        return res

    runs = [one_run(i) for i in range(max(1, int(args.failover_runs)))]
    first = runs[0]
    identical = None
    if len(runs) >= 2:
        identical = all(r["digest"] == first["digest"] for r in runs[1:])
    rtos = [x for r in runs for x in r["rtos"]]
    return {
        "failover_transport": "socket",
        "socket_failover_cycles": n_cycles,
        "socket_failover_runs": len(runs),
        "socket_failover_lost": sum(r["lost"] for r in runs),
        "socket_failover_lost_bound": sum(r["lost_bound"] for r in runs),
        "socket_failover_lost_over_bound": sum(
            r["over_bound"] for r in runs),
        "socket_failover_dup": sum(r["dup"] for r in runs),
        "socket_failover_rto_ms": round(max(rtos), 3) if rtos else None,
        "socket_failover_rto_ms_mean": (round(sum(rtos) / len(rtos), 3)
                                        if rtos else None),
        "socket_failover_recoveries": len(rtos),
        "socket_failover_matched_players": first["matched_players"],
        "socket_link_reconnects": sum(r["reconnects"] for r in runs),
        "heartbeat_false_positive_count": sum(
            r["hb_false_positives"] for r in runs),
        "socket_fenced_probe_failures": sum(
            r["fenced_probe_failures"] for r in runs),
        "socket_failover_transcript_identical": identical,
        "failover_transcript_digest": first["digest"],
    }


def bench_incident_soak(args) -> dict:
    """Incident-forensics soak (ISSUE 18, ``--incident-soak``): a seeded
    flash crowd + scripted lease-expiry failover + hard crash, with the
    black-box recorder armed — every trigger class the script exercises
    (slo_burn, slo_burn_clear, failover, crash_recovery) must auto-capture
    at least one bundle, the event spine's deterministic transcript must
    be bit-identical across two runs (the bar every other soak meets),
    capture p99 must stay <= 50 ms with ZERO rate-limiter drops, and
    ``scripts/postmortem.py`` must reconstruct the takeover root chain
    (lease expiry → epoch bump → replay window → takeover → burn →
    burn clear) OFFLINE from the persisted bundle alone.

    Script per run, three app boots on one replication fabric:
    host0 (primary + warm standby) takes a paced flash crowd against a
    deliberately unmeetable SLO target — the burn fires mid-burst and
    clears when the crowd drains; host0 is hard-killed and the standby
    promoted at scripted lease expiry; host1 adopts the shadow (failover
    bundle), takes a second crowd (its OWN burn/clear — the bundle whose
    spine holds the whole takeover chain), then hard-crashes; host2
    reboots on host1's journal (crash_recovery bundle) and stops clean."""
    import asyncio
    import hashlib
    import importlib.util
    import shutil
    import tempfile

    from matchmaking_tpu.config import (
        BatcherConfig,
        Config,
        DurabilityConfig,
        EngineConfig,
        ForensicsConfig,
        ObservabilityConfig,
        QueueConfig,
        ReplicationConfig,
    )
    from matchmaking_tpu.service.app import MatchmakingApp
    from matchmaking_tpu.service.broker import Properties
    from matchmaking_tpu.service.replication import ReplicationHub
    from matchmaking_tpu.testing.drain import fully_drained

    q = "incident.soak"
    pairs = int(args.incident_pairs)
    singles = int(args.incident_singles)
    lease_s = float(args.incident_lease_s)
    rate = max(1.0, float(args.incident_rate))
    #: The classes this script exercises — the >= 1-bundle-each gate.
    exercised = ("slo_burn", "slo_burn_clear", "failover", "crash_recovery")
    expected_chain = ["lease_expired", "epoch_bump", "replay_window",
                      "failover_takeover", "slo_burn", "slo_burn_clear"]

    def cfg_for(jdir: str, inc_dir: str, owner: "str | None") -> Config:
        return Config(
            queues=(QueueConfig(name=q, rating_threshold=50.0,
                                dedup_ttl_s=3600.0,
                                send_queued_ack=False),),
            engine=EngineConfig(backend="tpu", pool_capacity=4096,
                                pool_block=512, batch_buckets=(16, 64),
                                top_k=8, warm_start=True),
            # max_wait 5 ms >> the 1 ms SLO target below: EVERY settled
            # pair misses, so the flash crowd burns deterministically-in-
            # outcome (the burn EVENTS stay out of the transcript — only
            # their occurrence is gated, not their timing).
            batcher=BatcherConfig(max_batch=64, max_wait_ms=5.0),
            durability=DurabilityConfig(journal_dir=jdir, fsync="window"),
            observability=ObservabilityConfig(
                slo_target_ms=1.0, slo_objective=0.99,
                slo_fast_window_s=0.4, slo_slow_window_s=0.9,
                snapshot_interval_s=0.1, slow_trace_ms=1.0),
            forensics=ForensicsConfig(incident_dir=inc_dir,
                                      min_interval_s=0.25),
            replication=(ReplicationConfig(role="primary", owner=owner)
                         if owner else ReplicationConfig()),
        )

    def burst(host: int) -> "list[tuple[str, float]]":
        """The crash/failover-soak designed-load recipe: adjacent pairs
        MUST match whatever the framing; far singles never can."""
        rows: list[tuple[str, float]] = []
        for i in range(pairs):
            base = 1000.0 + i * 200.0
            rows.append((f"i{host}p{2 * i}", base))
            rows.append((f"i{host}p{2 * i + 1}", base + 1.0))
        for i in range(singles):
            rows.append((f"i{host}s{i}", 50_000.0 + host * 10_000.0
                         + i * 1_000.0))
        rng = np.random.default_rng(int(args.incident_seed) + host)
        rng.shuffle(rows)
        return rows

    async def publish_paced(app, reply_q: str, rows) -> None:
        for pid, rating in rows:
            app.broker.publish(
                q, f'{{"id":"{pid}","rating":{rating}}}'.encode(),
                Properties(reply_to=reply_q, correlation_id=pid))
            await asyncio.sleep(1.0 / rate)

    async def quiesce(app, rt, standby, matched_at_least: int,
                      replication: bool = True) -> bool:
        for _ in range(6000):
            await asyncio.sleep(0.005)
            if standby is not None:
                standby.pump()
            if fully_drained(app, rt, q, matched_at_least,
                             replication=replication):
                return True
        return False

    async def wait_capture(app, cls: str, timeout_s: float,
                           standby=None) -> bool:
        """Poll until the recorder has >= 1 bundle of ``cls`` (the
        telemetry loop keeps sampling / the burn monitors keep
        evaluating in the background)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if app.incidents.by_class.get(cls, 0) > 0:
                return True
            if standby is not None:
                standby.pump()
            await asyncio.sleep(0.05)
        return False

    def app_stats(app) -> "tuple[float | None, int]":
        lat = app.metrics.latency.get("incident_capture")
        p99 = (lat.percentile(99) * 1e3
               if lat is not None and len(lat) else None)
        return p99, app.incidents.dropped

    async def one_run(run_idx: int) -> dict:
        base_dir = tempfile.mkdtemp(prefix=f"mm_incident_r{run_idx}_")
        hub = ReplicationHub(lease_s=lease_s,
                             seed=int(args.incident_seed))
        by_class: dict[str, int] = {}
        transcripts: list[list] = []
        p99s: list[float] = []
        dropped = 0
        missed: list[str] = []
        clear_bundle_path = ""
        try:
            # -- host0: flash crowd -> burn -> clear -> hard kill -------
            app = MatchmakingApp(
                cfg_for(f"{base_dir}/host0", f"{base_dir}/inc0", "host0"),
                replication_hub=hub)
            await app.start()
            rt = app.runtime(q)
            reply_q = "incident.replies.0"
            app.broker.declare_queue(reply_q)
            app.broker.basic_consume(reply_q, lambda d: None,
                                     prefetch=1_000_000)
            standby = hub.standby(q, owner="host1")
            await publish_paced(app, reply_q, burst(0))
            if not await quiesce(app, rt, standby, 2 * pairs):
                log(f"[incident-soak r{run_idx} h0] WARNING: quiesce "
                    f"timed out")
            for cls, t in (("slo_burn", 3.0), ("slo_burn_clear", 5.0)):
                if not await wait_capture(app, cls, t, standby=standby):
                    missed.append(f"host0:{cls}")
            for k, v in app.incidents.by_class.items():
                by_class[k] = by_class.get(k, 0) + v
            p99, d = app_stats(app)
            if p99 is not None:
                p99s.append(p99)
            dropped += d
            transcripts.append(app.spine.transcript())
            await app.crash()
            standby.takeover(time.monotonic() + lease_s + 0.05)

            # -- host1: adoption (failover bundle) + its own burn/clear -
            app = MatchmakingApp(
                cfg_for(f"{base_dir}/host1", f"{base_dir}/inc1",
                        standby.owner),
                replication_hub=hub)
            await app.start()
            rt = app.runtime(q)
            reply_q = "incident.replies.1"
            app.broker.declare_queue(reply_q)
            app.broker.basic_consume(reply_q, lambda d: None,
                                     prefetch=1_000_000)
            if not await wait_capture(app, "failover", 2.0):
                missed.append("host1:failover")
            await publish_paced(app, reply_q, burst(1))
            # host1 is the terminal primary — no standby drains its
            # stream, so the replication-quiescence clause can't hold.
            if not await quiesce(app, rt, None, 2 * pairs,
                                 replication=False):
                log(f"[incident-soak r{run_idx} h1] WARNING: quiesce "
                    f"timed out")
            for cls, t in (("slo_burn", 3.0), ("slo_burn_clear", 5.0)):
                if not await wait_capture(app, cls, t):
                    missed.append(f"host1:{cls}")
            for k, v in app.incidents.by_class.items():
                by_class[k] = by_class.get(k, 0) + v
            p99, d = app_stats(app)
            if p99 is not None:
                p99s.append(p99)
            dropped += d
            transcripts.append(app.spine.transcript())
            # The persisted burn-clear bundle is the postmortem artifact:
            # its spine window holds the whole takeover chain.
            for f in sorted(os.listdir(f"{base_dir}/inc1")):
                if f.endswith("_slo_burn_clear.json"):
                    clear_bundle_path = os.path.join(f"{base_dir}/inc1", f)
            await app.crash()

            # -- host2: reboot on host1's journal (crash_recovery) ------
            app = MatchmakingApp(
                cfg_for(f"{base_dir}/host1", f"{base_dir}/inc2", None))
            await app.start()
            if not await wait_capture(app, "crash_recovery", 2.0):
                missed.append("host2:crash_recovery")
            for k, v in app.incidents.by_class.items():
                by_class[k] = by_class.get(k, 0) + v
            p99, d = app_stats(app)
            if p99 is not None:
                p99s.append(p99)
            dropped += d
            transcripts.append(app.spine.transcript())
            await app.stop()

            # -- offline postmortem on the persisted bundle -------------
            analysis = None
            if clear_bundle_path:
                spec = importlib.util.spec_from_file_location(
                    "mm_postmortem",
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "scripts", "postmortem.py"))
                pm = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(pm)
                with open(clear_bundle_path, encoding="utf-8") as f:
                    bundle = json.load(f)
                analysis = pm.analyze(bundle)
        finally:
            if not args.incident_keep_dirs:
                shutil.rmtree(base_dir, ignore_errors=True)
        blob = json.dumps(transcripts, sort_keys=True,
                          separators=(",", ":")).encode("utf-8")
        log(f"[incident-soak r{run_idx}] by_class={by_class} "
            f"dropped={dropped} capture_p99="
            f"{round(max(p99s), 3) if p99s else None} missed={missed}")
        return {
            "by_class": by_class,
            "dropped": dropped,
            "p99s": p99s,
            "missed": missed,
            "transcripts": transcripts,
            "digest": hashlib.sha256(blob).hexdigest(),
            "analysis": analysis,
        }

    runs = [asyncio.run(one_run(i))
            for i in range(max(1, int(args.incident_runs)))]
    first = runs[0]
    identical = None
    if len(runs) >= 2:
        identical = all(r["digest"] == first["digest"] for r in runs[1:])
    p99s = [x for r in runs for x in r["p99s"]]
    analysis = first["analysis"]
    chain = (analysis or {}).get("root_chain_kinds") or []
    return {
        "incident_runs": len(runs),
        "incident_captured": sum(sum(r["by_class"].values()) for r in runs),
        "incident_by_class": first["by_class"],
        "incident_classes_missed": [m for r in runs for m in r["missed"]],
        "incident_classes_ok": all(
            all(r["by_class"].get(cls, 0) >= 1 for cls in exercised)
            for r in runs),
        "incident_dropped": sum(r["dropped"] for r in runs),
        "incident_capture_ms_p99": (round(max(p99s), 3) if p99s else None),
        "incident_transcript_identical": identical,
        "incident_spine_digest": first["digest"],
        "incident_bundle_valid": (analysis is not None
                                  and not analysis["problems"]),
        "incident_root_chain": chain,
        "incident_root_chain_ok": chain == expected_chain,
    }


async def _scenario_cell(args, scn) -> dict:
    """One matrix cell: a fresh single-queue app driven by one scenario's
    seeded population load, with the autotuner closing the loop (unless
    ``--scenario-no-autotune``). The cell artifact is the full
    observability story — telemetry-ring trajectory, attribution shares,
    per-tier SLO attainment, quality, shed/expired, autotuner audit —
    not just a throughput number."""
    from matchmaking_tpu.config import (
        AutotuneConfig,
        BatcherConfig,
        BrokerConfig,
        ChaosConfig,
        Config,
        EngineConfig,
        ObservabilityConfig,
        OverloadConfig,
        QueueConfig,
    )
    from matchmaking_tpu.service.app import MatchmakingApp
    from matchmaking_tpu.service.loadgen import offered_load

    q = "matchmaking.search"
    tiers = scn.max_tier + 1 if scn.tiered else 1
    has_deadlines = any(c.deadline_ms > 0 for c in scn.cohorts)
    chaos = scn.chaos_config(q, seed=args.scenario_seed)
    slo_ms = float(args.scenario_slo_ms)
    cfg = Config(
        queues=(QueueConfig(rating_threshold=100.0,
                            send_queued_ack=False),),
        engine=EngineConfig(
            backend="tpu", pool_capacity=8192, pool_block=2048,
            batch_buckets=(16, 64, 256), top_k=8, pipeline_depth=2,
            warm_start=True),
        # The cell's STATIC base config is deliberately mid-range (window
        # wait included) — the point of the matrix is watching the tuner
        # move it per workload, and diffing the converged knobs.
        batcher=BatcherConfig(max_batch=256,
                              max_wait_ms=float(args.scenario_wait_ms)),
        broker=BrokerConfig(prefetch=8192),
        overload=OverloadConfig(
            max_waiting=int(args.scenario_max_waiting),
            tiers=tiers,
            deadline_sweep_ms=(25.0 if has_deadlines else 0.0)),
        chaos=chaos if chaos is not None else ChaosConfig(),
        observability=ObservabilityConfig(
            slo_target_ms=slo_ms, slo_objective=0.99,
            slo_fast_window_s=1.0, slo_slow_window_s=4.0,
            snapshot_interval_s=0.25),
        autotune=(AutotuneConfig() if args.scenario_no_autotune
                  else AutotuneConfig(interval_s=0.25,
                                      max_wait_ms_min=1.0)),
    )
    app = MatchmakingApp(cfg)
    try:
        # start() inside the try: a backend-outage abort (the advertised
        # cell-abort case) still runs the stop/no-op cleanup below.
        await app.start()
        res = await offered_load(
            app, q, rate=0.0, duration=0.0, seed=args.scenario_seed,
            scenario=scn, rate_scale=float(args.scenario_rate_scale),
            time_scale=float(args.scenario_time_scale))
        app.sample_telemetry()  # final trajectory point before teardown
        attr_q = app.attribution.snapshot()["queues"].get(q, {})
        cats = attr_q.get("categories") or {}
        hist = app.metrics.stages.get(q, {}).get("total")
        cell: dict = {
            "scenario": scn.name,
            "seed": args.scenario_seed,
            "rate_scale": float(args.scenario_rate_scale),
            "time_scale": float(args.scenario_time_scale),
            "duration_s": res.get("duration_s"),
            "scenario_digest": res.get("scenario_digest"),
            "offered": res["sent"],
            "sent_req_s": res["sent_req_s"],
            "matched": res["players_matched"],
            "matched_per_s": res["matched_per_s"],
            "queued_acks": res["queued_acks"],
            "shed": res["shed_requests"],
            "expired": res["expired_requests"],
            "retries_sent": res.get("retries_sent", 0),
            "cohorts": res.get("cohorts"),
            "slo_target_ms": slo_ms,
            "slo_attainment": attr_q.get("slo_attainment"),
            "admitted_p99_ms": (round(hist.percentile(99) * 1e3, 3)
                                if hist is not None and hist.count
                                else None),
            "attribution": {
                name: {"kind": cat.get("kind"), "share": cat.get("share")}
                for name, cat in cats.items()
            },
            "abort_reason": None,
        }
        if tiers > 1:
            per_tier = {}
            for t in range(tiers):
                good, total = app.attribution.slo_counts_tier(q, t)
                per_tier[str(t)] = {
                    "slo_good": good, "slo_total": total,
                    "attainment": (round(good / total, 4) if total
                                   else None),
                    "shed": int(app.metrics.counters.get(
                        f"shed_requests_t{t}")),
                    "expired": int(app.metrics.counters.get(
                        f"expired_requests_t{t}")),
                }
            cell["tiers"] = per_tier
        qentry = (app.quality.snapshot(q).get("queues") or {}).get(q)
        if qentry:
            tier_rows = qentry.get("tiers") or {}
            n_matched = qentry.get("matched_players") or 0
            # Matched-player-weighted aggregate over the tier rows (the
            # service ledger conditions on tier; the cell headline wants
            # the population view).
            q_sum = sum(r.get("quality_sum") or 0.0
                        for r in tier_rows.values())
            p10s = [r.get("quality_p10") for r in tier_rows.values()
                    if r.get("quality_p10") is not None]
            w99s = [r.get("wait_p99_s") for r in tier_rows.values()
                    if r.get("wait_p99_s") is not None]
            cell["quality"] = {
                "matched": n_matched,
                "quality_mean": (round(q_sum / n_matched, 6)
                                 if n_matched else None),
                "quality_p10": (round(min(p10s), 6) if p10s else None),
                "wait_p99_s": (round(max(w99s), 6) if w99s else None),
            }
        rt = app.runtime(q)
        if hasattr(rt.engine, "util_report"):
            u = rt.engine.util_report()
            cell["idle_fraction"] = u["idle_fraction"]
            cell["effective_occupancy"] = u["effective_occupancy"]
        cell["telemetry"] = app.telemetry.snapshot(
            limit=int(args.scenario_trajectory),
            prefixes=("idle_frac", "slo_good", "slo_total", "pool_size",
                      "stage_total_p99_ms", "batch_fill", "shed_total",
                      "expired_total"))
        if app.autotune is not None:
            cell["autotune"] = {
                "moves": app.autotune.moves,
                "failures": app.autotune.failures,
                "ticks": app.autotune.ticks,
                "knobs": app.autotune.knobs(),
                "trace": [list(row)
                          for row in app.autotune.decision_trace()],
            }
            if args.scenario_tuned_dir:
                os.makedirs(args.scenario_tuned_dir, exist_ok=True)
                path = os.path.join(args.scenario_tuned_dir,
                                    f"{scn.name}.json")
                with open(path, "w") as f:
                    json.dump(app.autotune.tuned_config(
                        scenario=scn.name, seed=args.scenario_seed),
                        f, indent=1, sort_keys=True)
                    f.write("\n")
                cell["tuned_config"] = path
        return cell
    finally:
        await app.stop()


def bench_modelcheck(args) -> dict:
    """Small-scope interleaving model check (ISSUE 19, ``--modelcheck``):
    bounded EXHAUSTIVE enumeration of action interleavings x fault
    injections over the real lease/replication/journal objects
    (analysis/modelcheck.py), no jax backend needed. Emits
    ``modelcheck_states_explored`` / ``modelcheck_violations`` /
    ``modelcheck_exhaustive`` (gated by scripts/bench_diff.py:
    violations under the zero-baseline rule) plus the minimized,
    digest-replayable counterexample when one exists."""
    from matchmaking_tpu.analysis.modelcheck import (
        ModelCheckConfig, run_modelcheck)

    cfg = ModelCheckConfig(
        queues=args.modelcheck_queues,
        depth=args.modelcheck_depth,
        admits=args.modelcheck_admits,
        settles=args.modelcheck_settles,
        faults=tuple(f for f in args.modelcheck_faults.split(",") if f),
        fault_budget=args.modelcheck_fault_budget,
        deadline_s=args.modelcheck_deadline_s or None,
    )
    return run_modelcheck(cfg)


def bench_modelcheck_mutations(args) -> dict:
    """Mutation gate for the model checker (ISSUE 19,
    ``--modelcheck-mutations``): break each fenced seam one at a time
    (skip the append fence, ack past the horizon, apply a gapped seq,
    publish from a stale epoch) and require every mutant to yield a
    minimized counterexample that replays bit-identically under its
    schedule digest — the checker's own falsifiability proof. Emits
    ``mutation_gate_passed`` (check.sh fails the build on False)."""
    from matchmaking_tpu.analysis.modelcheck import run_mutation_gate

    return run_mutation_gate()


def bench_scenario_matrix(args) -> dict:
    """The scenario observatory (ISSUE 13): run every requested scenario
    as one matrix cell — fresh app, seeded population load, autotuner
    closing the loop — and emit one artifact per cell. A cell abort
    (backend outage, cell crash) records the structured ``abort_reason``
    the PR 12 machinery introduced and the MATRIX continues; bench_diff
    skips aborted cells and gates the rest (slo_attainment /
    admitted_p99_ms / quality, direction-aware, matched by scenario
    name)."""
    import asyncio

    from matchmaking_tpu.scenario import load_scenario, scenario_names

    spec = args.scenario_matrix
    names = (scenario_names() if spec == "all"
             else [n.strip() for n in spec.split(",") if n.strip()])
    cells: list[dict] = []
    for name in names:
        log(f"[scenario] cell {name}")
        try:
            scn = load_scenario(name)
            cell = asyncio.run(_scenario_cell(args, scn))
            log(f"[scenario {name}] attainment="
                f"{cell.get('slo_attainment')} shed={cell.get('shed')} "
                f"admitted_p99={cell.get('admitted_p99_ms')} ms "
                f"autotune_moves="
                f"{(cell.get('autotune') or {}).get('moves')}")
        except Exception as e:
            # Structured per-CELL abort (ISSUE 13 satellite on the PR 12
            # machinery): a backend outage aborts this cell, not the
            # matrix — partials keep their reasons and bench_diff skips
            # them.
            log(f"[scenario {name}] ABORTED: {e!r}")
            reason = ("backend_unavailable"
                      if "backend" in repr(e).lower()
                      or "device" in repr(e).lower() else "cell_failed")
            cell = {"scenario": name, "abort_reason": reason,
                    "abort_detail": repr(e),
                    "abort_config": {
                        "seed": args.scenario_seed,
                        "rate_scale": args.scenario_rate_scale,
                        "time_scale": args.scenario_time_scale,
                        "slo_ms": args.scenario_slo_ms,
                    }}
        cells.append(cell)
    ok = [c for c in cells if c.get("abort_reason") is None]
    attainments = [c["slo_attainment"] for c in ok
                   if c.get("slo_attainment") is not None]
    return {
        "metric": (f"scenario-matrix worst-cell SLO attainment "
                   f"({len(ok)}/{len(cells)} cells)"),
        "value": (round(min(attainments), 4) if attainments else None),
        "unit": "attainment",
        "vs_baseline": None,
        "scenario_seed": args.scenario_seed,
        "scenario_matrix": cells,
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pool", type=int, default=100_000,
                   help="sustained concurrent pool size (headline: 100k)")
    p.add_argument("--capacity", type=int, default=131_072)
    p.add_argument("--pool-block", type=int, default=8192)
    p.add_argument("--window", type=int, default=4096,
                   help="requests per timed search window (default from the "
                        "round-4 sweep: (4096, depth 4, group 4) measured "
                        "53-62k matches/s at the best p99 of the high-"
                        "throughput points — BENCH_SWEEP.md §4)")
    p.add_argument("--windows", type=int, default=50,
                   help="measured windows")
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--repeats", type=int, default=3,
                   help="repeat the measured phase; report the median run "
                        "(the shared TPU backend has multi-tenant variance)")
    p.add_argument("--profile-dir", default="",
                   help="write a jax.profiler trace of the measured phase "
                        "(view with tensorboard/xprof)")
    p.add_argument("--depth", type=int, default=4,
                   help="max in-flight windows. MUST be >= readback-group "
                        "for groups to fill before the depth gate blocks. "
                        "The round-4 sweep (BENCH_SWEEP.md §4) found depth "
                        "beyond the group size only queues latency through "
                        "the tunnel (dispatch RPCs stall behind transfer "
                        "RPCs), so the default matches the group")
    p.add_argument("--readback-group", type=int, default=4,
                   help="stack k windows' results on device and transfer "
                        "them as ONE D2H. The tunnel's transfers are "
                        "latency-bound (~70 ms for 32 B or 24 KB alike) "
                        "and serialize at ~12-14/s, so grouping "
                        "multiplies result throughput ~k per transfer "
                        "slot (BENCH_SWEEP.md §3)")
    p.add_argument("--cpu-pool", type=int, default=2000,
                   help="CPU-oracle pool size (the reference's ~cap)")
    p.add_argument("--cpu-windows", type=int, default=20)
    p.add_argument("--skip-cpu", action="store_true")
    p.add_argument("--init-retries", type=int, default=5,
                   help="backend-init attempts before reporting "
                        "backend_unavailable (the tunnel has outages)")
    p.add_argument("--init-delay", type=float, default=60.0,
                   help="seconds between backend-init attempts")
    p.add_argument("--bucketed", action="store_true",
                   help="hierarchical rating-bucketed formation (ISSUE "
                        "14) for the engine phase: band-per-block "
                        "allocation + index-driven span formation "
                        "(bit-exact vs flat); the result row records "
                        "formation_touched_frac")
    p.add_argument("--pool-scale", default="",
                   help="sub-O(P) formation sweep (ISSUE 14): comma list "
                        "of synthetic pool sizes (e.g. "
                        "100000,300000,1000000) — one bucketed engine "
                        "per scale, rows under pool_scale with "
                        "matches_per_sec + formation_touched_frac "
                        "(bench_diff gates both per pool size)")
    p.add_argument("--pool-scale-window", type=int, default=512,
                   help="request window for the pool-scale sweep")
    p.add_argument("--pool-scale-windows", type=int, default=8,
                   help="measured windows per pool-scale cell")
    p.add_argument("--skip-roofline", action="store_true",
                   help="skip the chained device-step roofline phase")
    p.add_argument("--skip-e2e", action="store_true",
                   help="skip the service-level end-to-end latency phase")
    p.add_argument("--e2e-rate", type=float, default=6000.0,
                   help="Poisson arrival rate (req/s) for the e2e phase")
    p.add_argument("--e2e-seconds", type=float, default=6.0,
                   help="e2e phase duration")
    p.add_argument("--e2e-rates", default="12000,24000,48000,80000",
                   help="comma-separated offered rates for the saturation "
                        "sweep (finds the single-process knee); empty "
                        "string skips the sweep")
    p.add_argument("--e2e-max-waiting", type=int, default=0,
                   help="overload mode: bound the e2e phase's waiting pool "
                        "(OverloadConfig.max_waiting) so the saturation "
                        "sweep measures admitted-request latency under "
                        "explicit shedding (0 = unbounded, the default)")
    p.add_argument("--e2e-tier-mix", default="",
                   help="tiered QoS mode: per-class offered mix, e.g. "
                        "'0:0.2,1:0.5,2:0.3' — stamps seeded x-tier "
                        "headers, enables EDF cutting + lowest-tier-first "
                        "shedding, and emits per-tier p99/shed/expired "
                        "rows (e2e_tiers) in the BENCH json ('' = off)")
    p.add_argument("--e2e-quality", action="store_true",
                   help="quality/latency frontier phase (ISSUE 8): sweep "
                        "rating_threshold across fresh apps and record "
                        "per-point quality p10/p50/mean, wait-at-match "
                        "p99, and rating-bucket disparity as e2e_frontier "
                        "rows — the baseline any future match-objective "
                        "kernel must beat")
    p.add_argument("--e2e-quality-thresholds", default="25,50,100,200,400",
                   help="comma-separated rating_threshold sweep for the "
                        "frontier phase")
    p.add_argument("--e2e-quality-rate", type=float, default=600.0,
                   help="offered req/s per frontier point (kept modest: "
                        "the frontier is a shape measurement and must "
                        "complete on the CPU-mesh fallback)")
    p.add_argument("--e2e-quality-seconds", type=float, default=3.0,
                   help="measured duration per frontier point")
    p.add_argument("--e2e-quality-widen", type=float, default=0.0,
                   help="widen_per_sec applied at every frontier point "
                        "(0 = pure threshold sweep)")
    p.add_argument("--e2e-quality-sigma", type=float, default=150.0,
                   help="iid rating stddev for frontier arrivals (diverse "
                        "ratings, NOT the loadgen's paired default — the "
                        "threshold must bite for quality/wait to trade)")
    p.add_argument("--e2e-ab-seconds", type=float, default=0.0,
                   help="consume-share A/B phase (ISSUE 12): run the same "
                        "seeded load through consume_batch=on and =off "
                        "apps for this many seconds each and record the "
                        "measured consume+decode work reduction "
                        "(e2e_consume_ab). 0 = skip (two extra app boots "
                        "+ warmups)")
    p.add_argument("--e2e-ab-rate", type=float, default=4000.0,
                   help="offered req/s for the consume-share A/B phase")
    p.add_argument("--e2e-quality-spec", action="store_true",
                   help="add the speculation axis to the --e2e-quality "
                        "frontier (ISSUE 16): rerun every threshold point "
                        "with spec_formation on (e2e_frontier_spec rows) "
                        "and gate the per-rating-bucket quality disparity "
                        "no worse than the spec-off point "
                        "(e2e_frontier_spec_disparity_ok)")
    p.add_argument("--spec-ab", action="store_true",
                   help="speculative-formation A/B phase (ISSUE 16): the "
                        "same seeded widening-driven load through "
                        "spec_formation=on and =off apps, recording "
                        "turnaround p50/p99, spec hit rate, and the "
                        "wasted-step fraction (e2e_spec_ab + top-level "
                        "spec_* columns gated by scripts/bench_diff.py)")
    p.add_argument("--spec-ab-rate", type=float, default=600.0,
                   help="offered req/s for the spec A/B phase (low on "
                        "purpose: idle window gaps are the regime the "
                        "speculative overlap exists to fill)")
    p.add_argument("--spec-ab-seconds", type=float, default=4.0,
                   help="duration of each spec A/B leg")
    p.add_argument("--e2e-sweep-seconds", type=float, default=4.0,
                   help="duration of each saturation-sweep step")
    p.add_argument("--e2e-slo-ms", type=float, default=250.0,
                   help="e2e SLO target (ms): a request is GOOD when served "
                        "within this end to end; the BENCH json records "
                        "attainment + burn trajectories "
                        "(ObservabilityConfig.slo_target_ms)")
    p.add_argument("--no-cpu-fallback", action="store_true",
                   help="on persistent TPU init failure, print the bare "
                        "backend_unavailable line instead of falling back "
                        "to the CPU-mesh comms/e2e configs")
    p.add_argument("--fallback-skip-comms", action="store_true",
                   help="skip the comms-accounting phase in cpu-fallback "
                        "mode (it compiles the sharded team/role kernel "
                        "sets, ~minutes on a slow host)")
    p.add_argument("--skip-multiproc", action="store_true",
                   help="skip the multi-process ingress phase")
    p.add_argument("--mp-rate", type=float, default=80000.0,
                   help="offered req/s per self-driving multiproc worker "
                        "(above the ~77k/s single-process ceiling so the "
                        "phase measures saturation, not the offered rate)")
    p.add_argument("--mp-seconds", type=float, default=4.0)
    p.add_argument("--mp-deadline-ms", type=float, default=0.0,
                   help="stamp x-deadline on every multiproc loadgen "
                        "request (overload mode; 0 = off)")
    p.add_argument("--latency", action="store_true",
                   help="latency mode: small window, depth 1, grouping "
                        "off — reports the tunnel-floor-bounded measured "
                        "p50/p99 AND the projected PCIe-local latency "
                        "(batcher wait + host dispatch + device step), "
                        "then exits. The p99 < 50 ms north star is a "
                        "LATENCY claim; the default mode optimizes "
                        "throughput (BENCH_SWEEP.md §4)")
    p.add_argument("--latency-window", type=int, default=512)
    p.add_argument("--comms", action="store_true",
                   help="comms-accounting mode: build the sharded team/"
                        "role kernel sets at D=2/4/8, print one JSON row "
                        "per (family, D) with per-device per-step ICI "
                        "bytes + formation rows for the allgather vs ring "
                        "paths and an executed bit-exactness check, then "
                        "exit (BENCH_SWEEP.md §8). Needs >= D devices: "
                        "on CPU set "
                        "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    p.add_argument("--comms-capacity", type=int, default=65_536)
    p.add_argument("--comms-frontier-k", type=int, default=1024)
    p.add_argument("--placement-soak", action="store_true",
                   help="elastic placement soak (ISSUE 11): seeded load "
                        "through a hot queue while a scripted migrate → "
                        "promote(D=2) → demote → migrate-back cycle runs "
                        "through the audited controller path; emits "
                        "placement_blackout_ms_* / placement_lost / "
                        "placement_dup (bench_diff gates them, lower is "
                        "better). Standalone mode: skips every other "
                        "phase; on a CPU box a 4-virtual-device host "
                        "mesh is forced so the promote leg is real")
    p.add_argument("--placement-rate", type=float, default=2000.0,
                   help="soak offered load (req/s)")
    p.add_argument("--placement-seconds", type=float, default=4.0)
    p.add_argument("--placement-window", type=int, default=256,
                   help="soak batcher window / top batch bucket")
    p.add_argument("--placement-seed", type=int, default=17)
    p.add_argument("--crash-soak", action="store_true",
                   help="crash-restart soak (ISSUE 15): seeded load "
                        "through N kill/recover cycles (in-process hard "
                        "crash: no drain, no clean journal marker, "
                        "uncommitted buffers dropped) incl. one "
                        "device-lost D=2→1 demotion cycle; emits "
                        "crash_lost / crash_dup / crash_rto_ms_* / "
                        "journal write amplification / steady-state "
                        "append overhead (bench_diff gates them, lower "
                        "is better). Standalone mode: skips every other "
                        "phase; forces a host mesh so the sharded leg is "
                        "real on a CPU box")
    p.add_argument("--crash-cycles", type=int, default=3,
                   help="kill/recover cycles per run (last one is the "
                        "device-lost cycle)")
    p.add_argument("--crash-runs", type=int, default=2,
                   help="full soak repetitions; >= 2 additionally pins "
                        "the recovery transcripts bit-identical across "
                        "runs")
    p.add_argument("--crash-pairs", type=int, default=6,
                   help="matching pairs per cycle (deterministic designed "
                        "load)")
    p.add_argument("--crash-singles", type=int, default=3,
                   help="never-matching singles per cycle (the waiting "
                        "pool recovery must carry)")
    p.add_argument("--crash-rate", type=float, default=800.0,
                   help="publish pacing for the cycle load (req/s)")
    p.add_argument("--crash-seed", type=int, default=23)
    p.add_argument("--crash-compact-records", type=int, default=64,
                   help="live-segment record budget before compaction — "
                        "small by default so the soak exercises snapshot "
                        "rotation every cycle")
    p.add_argument("--crash-overhead-pairs", type=int, default=600,
                   help="pairs for the steady-state append-overhead "
                        "phase (fsync=window vs durability off)")
    p.add_argument("--crash-keep-dirs", action="store_true",
                   help="keep the per-run journal directories for "
                        "inspection")
    p.add_argument("--failover-soak", action="store_true",
                   help="hot-standby failover soak (ISSUE 17): seeded "
                        "load through N primary-kill/standby-takeover "
                        "cycles over the in-process replication link — "
                        "lease-expiry-fenced takeover, successor adopts "
                        "the standby's shadow, the last kill lands with "
                        "real replication lag behind a scripted link "
                        "partition. Emits failover_lost / failover_dup / "
                        "failover_rto_ms / replication_lag_ms_p99 "
                        "(bench_diff gates them, lower is better; "
                        "lost/dup under the zero-baseline rule). "
                        "Standalone mode: skips every other phase")
    p.add_argument("--failover-cycles", type=int, default=3,
                   help="kill/takeover cycles per run (last one is the "
                        "kill-under-lag cycle)")
    p.add_argument("--failover-runs", type=int, default=2,
                   help="full soak repetitions; >= 2 additionally pins "
                        "the takeover transcripts bit-identical across "
                        "runs")
    p.add_argument("--failover-pairs", type=int, default=6,
                   help="matching pairs per cycle (deterministic "
                        "designed load)")
    p.add_argument("--failover-singles", type=int, default=3,
                   help="never-matching singles per cycle (the adopted "
                        "waiting pool must carry them across hosts)")
    p.add_argument("--failover-late-singles", type=int, default=2,
                   help="singles published BEHIND the lag-cycle link "
                        "partition — the bounded loss the kill-under-lag "
                        "gate measures (0 disables the lag cycle)")
    p.add_argument("--failover-rate", type=float, default=800.0,
                   help="publish pacing for the cycle load (req/s)")
    p.add_argument("--failover-seed", type=int, default=29)
    p.add_argument("--failover-lease-s", type=float, default=0.4,
                   help="lease duration on the in-process authority "
                        "(takeover expiry is scripted on the authority's "
                        "clock, so the soak never sleeps it out)")
    p.add_argument("--failover-keep-dirs", action="store_true",
                   help="keep the per-host journal directories for "
                        "inspection")
    p.add_argument("--transport", default="inproc",
                   choices=("inproc", "socket", "socket-loopback"),
                   help="--failover-soak replication fabric (ISSUE 20): "
                        "'inproc' = the PR 17 in-process link; "
                        "'socket-loopback' = the SAME soak script over "
                        "real UDS sockets + a remote lease client in one "
                        "process (nemesis off — the in-proc ≡ socket "
                        "equivalence pin: transcripts must be "
                        "bit-identical to inproc on the same seed); "
                        "'socket' = CROSS-PROCESS soak: lease service + "
                        "host chain as subprocesses, SIGKILL mid-load "
                        "under the scripted network nemesis (incl. one "
                        "asymmetric partition and one mid-stream reset)")
    p.add_argument("--incident-soak", action="store_true",
                   help="incident-forensics soak (ISSUE 18): seeded flash "
                        "crowd + scripted lease-expiry failover + hard "
                        "crash with the black-box recorder armed — every "
                        "exercised trigger class must capture a bundle, "
                        "the spine transcript must be bit-identical "
                        "across runs, capture p99 <= 50ms with zero "
                        "rate-limiter drops, and scripts/postmortem.py "
                        "must reconstruct the takeover root chain offline "
                        "from the persisted bundle alone. Standalone "
                        "mode: skips every other phase")
    p.add_argument("--incident-pairs", type=int, default=30,
                   help="matching pairs per flash crowd (sized so the "
                        "paced burst outlasts the slow burn window)")
    p.add_argument("--incident-singles", type=int, default=6,
                   help="never-matching singles per flash crowd")
    p.add_argument("--incident-rate", type=float, default=30.0,
                   help="publish pacing for the flash crowd (req/s); the "
                        "default keeps the burst > slo_slow_window_s so "
                        "the burn fires mid-burst")
    p.add_argument("--incident-runs", type=int, default=2,
                   help="soak repetitions; >= 2 additionally pins the "
                        "spine transcripts bit-identical across runs")
    p.add_argument("--incident-seed", type=int, default=31)
    p.add_argument("--incident-lease-s", type=float, default=0.5,
                   help="lease duration on the in-process authority "
                        "(takeover expiry is scripted on the authority's "
                        "clock)")
    p.add_argument("--incident-keep-dirs", action="store_true",
                   help="keep the per-run journal + incident directories "
                        "for inspection")
    p.add_argument("--modelcheck", action="store_true",
                   help="standalone: bounded exhaustive interleaving "
                        "model check of the lease/replication/failover "
                        "protocol on the REAL objects "
                        "(analysis/modelcheck.py) — no backend needed; "
                        "emits modelcheck_* metrics and a minimized "
                        "digest-replayable counterexample on violation")
    p.add_argument("--modelcheck-mutations", action="store_true",
                   help="standalone: the model checker's mutation gate — "
                        "break each fenced seam one at a time and "
                        "require a minimized counterexample per mutant "
                        "(mutation_gate_passed)")
    p.add_argument("--modelcheck-queues", type=int, default=2,
                   help="modelcheck scope: queues sharing one lease "
                        "authority")
    p.add_argument("--modelcheck-depth", type=int, default=6,
                   help="modelcheck scope: schedule length bound")
    p.add_argument("--modelcheck-admits", type=int, default=2,
                   help="modelcheck scope: admit windows per queue")
    p.add_argument("--modelcheck-settles", type=int, default=1,
                   help="modelcheck scope: terminal settles per queue")
    p.add_argument("--modelcheck-faults",
                   default="expire,crash,drop,dup",
                   help="modelcheck scope: comma list from "
                        "expire,crash,drop,dup,reorder,partition")
    p.add_argument("--modelcheck-fault-budget", type=int, default=2,
                   help="modelcheck scope: total fault actions per "
                        "schedule")
    p.add_argument("--modelcheck-deadline-s", type=float, default=0.0,
                   help="modelcheck wall-clock cap in seconds (0 = "
                        "none; hitting it reports exhaustive=false)")
    p.add_argument("--scenario-matrix", default="",
                   help="scenario observatory (ISSUE 13): run the named "
                        "population-model scenarios (comma list, or 'all' "
                        "for every configs/scenarios/*.json) as a soak "
                        "matrix — one fresh app per cell, seeded arrival "
                        "transcript, autotuner closing the loop — and "
                        "emit per-cell telemetry-trajectory + attribution "
                        "+ SLO/quality/shed artifacts (scenario_matrix "
                        "rows, gated by scripts/bench_diff.py). "
                        "Standalone mode: skips every other phase")
    p.add_argument("--scenario-seed", type=int, default=21,
                   help="arrival/chaos seed for every matrix cell")
    p.add_argument("--scenario-rate-scale", type=float, default=1.0,
                   help="multiply every scenario segment's offered rate")
    p.add_argument("--scenario-time-scale", type=float, default=1.0,
                   help="compress/stretch every scenario's curve "
                        "(0.5 = replay in half the time)")
    p.add_argument("--scenario-slo-ms", type=float, default=100.0,
                   help="per-cell SLO target (ms) — also the autotuner's "
                        "steering target")
    p.add_argument("--scenario-wait-ms", type=float, default=25.0,
                   help="each cell's STATIC batcher window wait; the "
                        "autotuner tightens it per workload (the knob "
                        "trajectory is the artifact's point)")
    p.add_argument("--scenario-max-waiting", type=int, default=2048,
                   help="per-cell admission waiting-pool cap "
                        "(OverloadConfig.max_waiting)")
    p.add_argument("--scenario-trajectory", type=int, default=120,
                   help="telemetry-ring snapshots embedded per cell")
    p.add_argument("--scenario-no-autotune", action="store_true",
                   help="run the matrix with static knobs (the baseline "
                        "the closed-loop win is measured against)")
    p.add_argument("--scenario-tuned-dir", default="",
                   help="write each cell's converged knob artifact to "
                        "<dir>/<scenario>.json (the configs/tuned/ "
                        "capacity artifacts)")
    args = p.parse_args()
    if args.modelcheck:
        # Standalone, pure host-side: the checker drives the real
        # replication objects under a virtual clock — no jax backend,
        # no broker, deterministic by construction.
        print(json.dumps(bench_modelcheck(args)), flush=True)
        return
    if args.modelcheck_mutations:
        print(json.dumps(bench_modelcheck_mutations(args)), flush=True)
        return
    if args.crash_soak:
        # Standalone like --placement-soak: the device-lost cycle needs a
        # D=2 mesh, so force >= 2 host devices before any jax import (a
        # no-op on a real TPU backend — the flag only affects the CPU
        # platform).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()
        print(json.dumps(bench_crash_soak(args)), flush=True)
        return
    if args.failover_soak:
        # Standalone like --crash-soak: one queue, CPU-harness friendly
        # (no mesh needed — the failover axis is hosts, not devices).
        print(json.dumps(bench_failover_soak(args)), flush=True)
        return
    if args.incident_soak:
        # Standalone like --failover-soak: one queue, CPU-harness
        # friendly; the forensics axis is the event spine + recorder.
        print(json.dumps(bench_incident_soak(args)), flush=True)
        return
    if args.scenario_matrix:
        # Standalone like --placement-soak: the matrix is its own
        # artifact. Cells run on whatever backend jax initializes (the
        # check.sh smoke pins JAX_PLATFORMS=cpu); a backend outage aborts
        # cells, not the process.
        print(json.dumps(bench_scenario_matrix(args)), flush=True)
        return
    if args.placement_soak:
        # Before any jax import: the soak needs >= 2 devices for the
        # migrate legs (4 for the shard cycle).  The host-platform flag
        # is set UNCONDITIONALLY — it only affects the CPU platform, so
        # it is a no-op on a real TPU backend, and gating it on
        # JAX_PLATFORMS would leave a bare-env CPU box at 1 device where
        # every scripted action is refused and the gate passes vacuously.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()
        print(json.dumps(bench_placement_soak(args)), flush=True)
        return
    if args.comms:
        for row in comms_accounting_rows(capacity=args.comms_capacity,
                                         frontier_k=args.comms_frontier_k):
            print(json.dumps(row), flush=True)
        return
    if args.latency:
        # Latency operating point: one small window in flight, no
        # grouping (grouping trades first-window latency for transfer
        # throughput), tighter batcher wait.
        args.window = args.latency_window
        args.depth = 1
        args.readback_group = 1
        args.skip_e2e = True
        args.skip_multiproc = True
        args.skip_cpu = True
    if args.depth < args.readback_group:
        log(f"[warn] depth {args.depth} < readback-group "
            f"{args.readback_group}: groups can never fill before the "
            f"depth gate blocks; grouping degrades to loose partial seals")

    if os.environ.get("MM_BENCH_CPU_FALLBACK") == "1":
        # Re-exec'd by run_cpu_fallback with a clean interpreter pinned to
        # the CPU backend — go straight to the fallback phases.
        run_cpu_fallback(args)
        return

    devices = init_backend(attempts=args.init_retries, delay_s=args.init_delay)
    if devices is None:
        if args.no_cpu_fallback:
            # One parseable line, rc=0: the driver records the outage
            # itself rather than an evidence-less crashed round (round-2
            # postmortem). abort_reason is the structured form (ISSUE 12
            # satellite): bench_diff skips aborted rounds by it, and the
            # config echo makes the lost round reproducible.
            print(json.dumps({
                "metric": f"matches/sec @ {args.pool}-player pool (1v1 ELO)",
                "value": None,
                "unit": "matches/sec",
                "vs_baseline": None,
                "error": "backend_unavailable",
                "abort_reason": "backend_unavailable",
                "abort_detail": (f"TPU init failed after "
                                 f"{args.init_retries} attempts"),
                "abort_config": {"pool": args.pool, "window": args.window,
                                 "depth": args.depth,
                                 "readback_group": args.readback_group},
            }), flush=True)
            return
        # ROADMAP carry-over (BENCH_r05): a dead backend still yields a
        # partial trajectory point on the CPU-mesh configs.
        run_cpu_fallback(args)
        return

    import jax

    log(f"jax {jax.__version__} devices={devices}")

    tpu = bench_tpu(args)
    if args.latency:
        # Projection to PCIe-local hardware: every component is measured on
        # THIS run except the transfer channel it removes. alloc/pack are
        # host-only (hardware-independent); h2d is kept at the measured
        # tunnel value (conservative — PCIe is faster); device_step_ms is
        # the chained on-device step time. The batcher contributes up to
        # max_wait_ms (3.0 in the service default): half in the median
        # case, the full wait plus one queued step at p99.
        spans = tpu.get("spans", {})
        host_ms = sum(spans.get(k, 0.0) for k in
                      ("alloc_ms_avg", "pack_ms_avg", "h2d_ms_avg"))
        step_ms = tpu.get("device_step_ms") or 0.0
        batcher_wait_ms = 3.0
        proj_p50 = round(batcher_wait_ms / 2 + host_ms + step_ms, 2)
        proj_p99 = round(batcher_wait_ms + host_ms + 2 * step_ms, 2)
        print(json.dumps({
            "metric": (f"p99 match latency @ {args.pool}-player pool "
                       "(1v1 ELO, latency preset)"),
            "value": round(tpu["p99_ms"], 3),
            "unit": "ms",
            "vs_baseline": None,
            "p50_ms": round(tpu["p50_ms"], 3),
            "p99_target_ms": 50.0,
            "window": args.window,
            "depth": 1,
            "readback_group": 1,
            "matches_per_sec": round(tpu["matches_per_sec"], 1),
            "device_step_ms": tpu.get("device_step_ms"),
            "host_dispatch_ms": round(host_ms, 3),
            "projected_local_p50_ms": proj_p50,
            "projected_local_p99_ms": proj_p99,
            "projection_formula": (
                "p50 = max_wait/2 + alloc+pack+h2d + device_step; "
                "p99 = max_wait + alloc+pack+h2d + 2*device_step "
                "(measured spans; removes only the tunnel's ~70 ms "
                "serialized D2H, which PCIe-local hardware does not have)"),
            "note": ("measured p50/p99 include the axon tunnel's ~70 ms "
                     "fixed D2H latency (BENCH_SWEEP.md §1) — the floor "
                     "below which no number through THIS harness can go"),
        }), flush=True)
        return
    e2e = {}
    if not args.skip_e2e:
        try:
            e2e = bench_e2e(args)
            log(f"[e2e] {e2e}")
        except Exception as e:
            log(f"[e2e] failed: {e!r}")
    if args.e2e_quality:
        try:
            e2e.update(bench_quality_frontier(args))
        except Exception as e:
            log(f"[e2e-quality] failed: {e!r}")
    if args.e2e_ab_seconds > 0:
        try:
            e2e.update(bench_consume_ab(args))
        except Exception as e:
            log(f"[e2e-consume-ab] failed: {e!r}")
    if args.spec_ab:
        try:
            e2e.update(bench_spec_ab(args))
        except Exception as e:
            # Aborts (chip-less boxes included) leave the spec_* columns
            # absent — bench_diff skips metrics missing on either side.
            log(f"[spec-ab] failed: {e!r}")
    mp = {}
    if not args.skip_multiproc:
        try:
            mp = bench_multiproc(args)
        except Exception as e:
            log(f"[multiproc] failed: {e!r}")
    pool_scale: list = []
    if args.pool_scale:
        try:
            pool_scale = bench_pool_scale(args)
        except Exception as e:
            log(f"[pool-scale] failed: {e!r}")
    if args.skip_cpu:
        # None, not NaN: NaN is not valid RFC 8259 JSON and breaks strict
        # parsers on the driver side.
        cpu = {"matches_per_sec": None}
        vs = None
    else:
        cpu = bench_cpu_oracle(args)
        vs = (round(tpu["matches_per_sec"] / cpu["matches_per_sec"], 2)
              if cpu["matches_per_sec"] > 0 else None)

    result = {
        "metric": f"matches/sec @ {args.pool}-player pool (1v1 ELO)",
        "value": round(tpu["matches_per_sec"], 1),
        "unit": "matches/sec",
        "vs_baseline": vs,
        "p50_ms": round(tpu["p50_ms"], 3),
        "p99_ms": round(tpu["p99_ms"], 3),
        "p99_target_ms": 50.0,
        "pool": tpu["pool"],
        "window": tpu["window"],
        "total_matches": tpu["total_matches"],
        "all_runs_mps": tpu.get("all_runs_mps", []),
        **e2e,
        **mp,
        **({"pool_scale": pool_scale} if pool_scale else {}),
        # The headline sub-O(P) number (ISSUE 14): the largest measured
        # pool's touched fraction (max by pool, not CLI order — rounds
        # must gate like against like), falling back to the engine
        # phase's (present when --bucketed).
        **({"formation_touched_frac":
            (max(pool_scale,
                 key=lambda r: r.get("pool", 0))
             .get("formation_touched_frac")
             if pool_scale else tpu.get("formation_touched_frac"))}
           if (pool_scale or tpu.get("formation_touched_frac") is not None)
           else {}),
        "hot_path_recompiles": tpu.get("hot_path_recompiles"),
        "device_step_ms": tpu.get("device_step_ms"),
        "hbm_bytes_per_s": tpu.get("hbm_bytes_per_s"),
        "hbm_util_vs_819GBps": tpu.get("hbm_util_vs_819GBps"),
        "pair_scores_per_s": tpu.get("pair_scores_per_s"),
        "baseline": {
            "what": "CPU oracle (reference sequential-scan semantics) "
                    f"@ {args.cpu_pool}-player pool",
            "matches_per_sec": (None if cpu["matches_per_sec"] is None
                                else round(cpu["matches_per_sec"], 1)),
        },
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
