"""Test doubles and dynamic checkers shipped with the package (usable by
downstream users' suites as well as our own CI):

- ``fake_pika`` — the in-memory pika fake that lets the AMQP adapter run
  without a RabbitMQ server;
- ``sanitizer`` — the runtime async sanitizer (instrumented asyncio.Lock:
  lock-order-inversion detection, runtime await-under-lock, event-loop
  stall watchdog) that the soak/chaos suites run under via the
  ``sanitizer`` pytest fixture.
"""
