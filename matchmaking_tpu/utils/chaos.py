"""Deterministic chaos-schedule runtime (config.ChaosConfig is the script).

The reference's resilience story is probabilistic soak testing; the rebuild's
fault soaks were timing-flaky because the broker's fault RNG is shared and
its call ORDER depends on event-loop scheduling. This module makes every
fault decision a pure function of *message identity* (per-queue publish
sequence number + redelivery attempt) or *device-step index*, so a chaos run
replays bit-identically under any interleaving:

- ``ChaosState`` — one per app: per-queue broker fault decisions
  (drop/dup/partition) and the registry of per-queue engine hooks.
- ``EngineChaosHook`` — one per queue, owned by the QUEUE RUNTIME and
  re-attached to every fresh engine, so device-step indices keep advancing
  across engine revives: a schedule failing steps 0-2 trips the circuit
  breaker instead of re-failing step 0 on each fresh engine forever.

Engine hooks cover SEARCH steps and breaker probes only. Admission, evict
and restore dispatches are exempt by design: they are the crash-recovery
path itself, and a schedule that could fail a revive would turn every
injected crash into unrecoverable pool loss instead of the degradation the
breaker exists to test.
"""

from __future__ import annotations

import zlib

from matchmaking_tpu.config import ChaosConfig

_MASK = (1 << 64) - 1


class ChaosInjectedError(RuntimeError):
    """Raised at a scripted chaos fault point (device step / probe)."""


class ChaosDeviceLostError(ChaosInjectedError):
    """A scripted DEVICE-LOSS fault (ISSUE 15): models a mesh participant
    dying mid-serve — the XLA "device lost / data transfer failed" error
    class, which a plain revive-from-mirror cannot fix because the rebuilt
    engine would bind the same dead chip. The queue runtime routes it
    through the breaker's crash accounting into the failover path (demote
    a sharded queue to its surviving devices) instead of revive-looping.

    ``device`` is the LOGICAL index within the queue's binding that died
    (-1 = the last device, the schedule default)."""

    def __init__(self, message: str, device: int = -1):
        super().__init__(message)
        self.device = device


def _mix(h: int) -> int:
    """splitmix64 finalizer — full-avalanche 64-bit mix."""
    h = (h ^ (h >> 30)) * 0xBF58476D1CE4E5B9 & _MASK
    h = (h ^ (h >> 27)) * 0x94D049BB133111EB & _MASK
    return h ^ (h >> 31)


def hash01(seed: int, *keys: "int | str") -> float:
    """Deterministic uniform [0, 1) from (seed, *keys). Strings hash via
    crc32, not builtin ``hash`` — PYTHONHASHSEED must not change a chaos
    schedule between runs."""
    h = _mix(seed & _MASK ^ 0x9E3779B97F4A7C15)
    for k in keys:
        if isinstance(k, str):
            k = zlib.crc32(k.encode())
        h = _mix(h ^ (k & _MASK))
    return h / float(1 << 64)


class EngineChaosHook:
    """Per-queue device-step fault stream. The counters live HERE — outside
    the engine — so scripted step indices survive engine revives (see module
    docstring). Attached to engines by the queue runtime; ``None`` hook on
    an engine means no chaos."""

    __slots__ = ("cfg", "queue", "events", "steps", "probes", "_fail",
                 "_ranges", "_lost")

    def __init__(self, cfg: ChaosConfig, queue: str = "", events=None):
        self.cfg = cfg
        self.queue = queue
        #: Lifecycle event log (utils/trace.EventLog) or None: every
        #: injected fault lands on the /debug/events timeline next to the
        #: breaker trips it causes — a chaos soak reads as a narrative.
        self.events = events
        self.steps = 0
        self.probes = 0
        self._fail = frozenset(cfg.fail_steps)
        self._ranges = tuple(cfg.fail_step_ranges)
        self._lost = frozenset(cfg.device_lost_steps)

    def on_step(self) -> None:
        """One device SEARCH-step dispatch is about to run. Raises
        ChaosInjectedError at scripted indices; the engine must call this
        BEFORE mutating any state for the chunk."""
        idx = self.steps
        self.steps += 1
        if idx in self._lost:
            # Device loss BEFORE the plain step faults: a schedule naming
            # the same index means the stronger fault (the one the
            # failover path must absorb) wins.
            if self.events is not None:
                self.events.append("chaos_device_lost", self.queue,
                                   f"step {idx}")
            raise ChaosDeviceLostError(
                f"chaos: scripted device loss at step index {idx}",
                device=self.cfg.device_lost_device)
        if idx in self._fail or any(a <= idx < b for a, b in self._ranges):
            if self.events is not None:
                self.events.append("chaos_step_fault", self.queue,
                                   f"step {idx}")
            raise ChaosInjectedError(
                f"chaos: scripted device-step failure at step index {idx}")

    def on_probe(self) -> None:
        """One half-open breaker probe is about to run (separate stream from
        on_step so probe outcomes are scriptable independently of how many
        traffic steps a crash storm consumed)."""
        idx = self.probes
        self.probes += 1
        if idx < self.cfg.fail_probes:
            if self.events is not None:
                self.events.append("chaos_probe_fault", self.queue,
                                   f"probe {idx}")
            raise ChaosInjectedError(
                f"chaos: scripted probe failure (probe index {idx})")


class ChaosState:
    """Mutable per-run chaos bookkeeping. One instance per app; the broker
    consults it for fault decisions, queue runtimes pull their engine hooks
    from it. All decisions are pure functions of (seed, queue, seq[,
    attempt]) — see module docstring."""

    def __init__(self, cfg: ChaosConfig):
        self.cfg = cfg
        #: Lifecycle event log (set by the app); propagated to every engine
        #: hook created AFTER assignment — assign before runtimes boot.
        self.events = None
        self._queues = frozenset(cfg.queues)
        self._drop_seqs = frozenset(cfg.drop_seqs)
        self._dup_seqs = {int(s): int(n) for s, n in cfg.dup_seqs}
        self._hooks: dict[str, EngineChaosHook] = {}

    def applies(self, queue: str) -> bool:
        return not self._queues or queue in self._queues

    # ---- broker faults ----------------------------------------------------

    def consume_faults(self) -> bool:
        return self.cfg.consume_faults()

    def publish_faults(self) -> bool:
        return self.cfg.publish_faults()

    def should_drop(self, queue: str, seq: int, attempt: int) -> bool:
        """Consume-side drop decision for delivery ``seq`` on its
        ``attempt``-th processing try (0 = first). Scripted drop_seqs hit
        the first attempt only — the redelivery must make progress."""
        if seq < 0 or not self.applies(queue):
            return False
        if attempt == 0 and seq in self._drop_seqs:
            return True
        p = self.cfg.drop_prob
        return p > 0 and hash01(self.cfg.seed, "drop", queue, seq, attempt) < p

    def dup_copies(self, queue: str, seq: int) -> int:
        """Extra delivery copies to enqueue for publish ``seq``."""
        if not self.applies(queue):
            return 0
        extra = self._dup_seqs.get(seq, 0)
        p = self.cfg.dup_prob
        if p > 0 and hash01(self.cfg.seed, "dup", queue, seq) < p:
            extra += 1
        return extra

    def partition_action(self, queue: str, seq: int) -> str | None:
        """"pause"/"resume"/None for publish ``seq`` on ``queue``. Publishes
        are sequential per queue, so exact-index matching suffices."""
        if not self.applies(queue):
            return None
        for pause_seq, resume_seq in self.cfg.partitions:
            if seq == resume_seq:
                return "resume"
            if seq == pause_seq:
                return "pause"
        return None

    # ---- engine hooks -----------------------------------------------------

    def engine_hook(self, queue: str) -> EngineChaosHook:
        hook = self._hooks.get(queue)
        if hook is None:
            hook = EngineChaosHook(self.cfg, queue, self.events)
            self._hooks[queue] = hook
        return hook
