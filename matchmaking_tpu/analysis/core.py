"""matchlint core: findings, ignore comments, baseline, source discovery.

The analyzer is project-specific by design (SURVEY.md §7 "Hard parts"):
its rules encode THIS codebase's concurrency contract — the service
serializes all engine access behind ``_engine_lock``, engines are
single-writer objects driven through ``asyncio.to_thread``, and chaos
replay determinism forbids unseeded RNGs. Generic linters can't see any of
that; PR 2 paid for the gap by rediscovering three statically-detectable
races with a seeded chaos schedule.

Vocabulary shared by every rule module:

- ``Finding`` — one violation: rule, file, line, message, plus a
  ``context`` (the enclosing ``Class.method`` qualname) that anchors the
  baseline fingerprint so line drift doesn't churn the baseline.
- ``# matchlint: ignore[rule-a,rule-b] <reason>`` — inline suppression on
  the offending line or the line directly above it. The reason is
  REQUIRED: a bare ignore is inactive (the finding still reports), so
  every suppression documents why the pattern is intentional.
- ``analysis/baseline.json`` — checked-in fingerprints of accepted
  findings (empty when the gate is clean). ``--write-baseline``
  regenerates it; entries carry a ``reason`` like inline ignores do.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable

#: Every rule the suite ships (rule modules register against these names).
RULES = (
    "await-under-lock",
    "guarded-by",
    "blocking-call",
    "determinism",
    "recompile",
    "perf",
    "settlement",
    "lock-pairing",
    "device",
    "stale-ignore",
    "speculation",
    "protocol",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    #: Enclosing ``Class.method`` (or module-level ``<module>``): the
    #: baseline anchor — stable across unrelated line churn.
    context: str = ""

    def fingerprint(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.context)

    def render(self) -> str:
        where = f" (in {self.context})" if self.context else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{where}"


_IGNORE_RE = re.compile(
    r"#\s*matchlint:\s*ignore\[([a-z\-, ]+)\]\s*(\S.*)?")


def _comment_lines(lines: list[str],
                   source: str | None) -> "list[tuple[int, str]]":
    """(lineno, comment text) for every REAL comment token.  Tokenizing
    (rather than regex over raw lines) keeps ignore syntax quoted inside
    docstrings and test-fixture strings from registering as live ignores —
    which the stale-ignore rule would otherwise flag forever."""
    if source is None:
        return list(enumerate(lines, start=1))
    import io
    import tokenize

    try:
        return [(tok.start[0], tok.string)
                for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline)
                if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        return list(enumerate(lines, start=1))


class IgnoreMap:
    """Per-file map of line → rules suppressed there. An ignore covers its
    own line and the line below it (so a comment can sit above a long
    statement). Ignores without a reason are INACTIVE.

    Usage is tracked per (comment line, rule): an active ignore that
    suppresses nothing in a full-rules run becomes a ``stale-ignore``
    finding itself (suppression hygiene — dead ignores hide future real
    findings at the same line)."""

    def __init__(self, lines: list[str], source: str | None = None):
        #: line → {(rule, owning comment line)}.
        self._by_line: dict[int, set[tuple[str, int]]] = {}
        #: (comment line, rules named there) for every ACTIVE ignore.
        self.entries: list[tuple[int, frozenset[str]]] = []
        self.bare: list[int] = []  # ignores missing the required reason
        #: (comment line, rule) pairs that suppressed at least one finding
        #: this run (filled by apply_ignores).
        self.used: set[tuple[int, str]] = set()
        for i, text in _comment_lines(lines, source):
            m = _IGNORE_RE.search(text)
            if not m:
                continue
            if not (m.group(2) or "").strip():
                self.bare.append(i)
                continue
            rules = frozenset(r.strip() for r in m.group(1).split(",")
                              if r.strip())
            self.entries.append((i, rules))
            for rule in rules:
                self._by_line.setdefault(i, set()).add((rule, i))
                self._by_line.setdefault(i + 1, set()).add((rule, i))

    def suppressed(self, line: int, rule: str) -> bool:
        for r, comment_line in self._by_line.get(line, ()):
            if r == rule:
                self.used.add((comment_line, rule))
                return True
        return False


class SourceFile:
    """One parsed source file: text, lines, AST, and its ignore map."""

    def __init__(self, root: str, relpath: str):
        self.root = root
        self.path = relpath.replace(os.sep, "/")
        with open(os.path.join(root, relpath), encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=relpath)
        self.ignores = IgnoreMap(self.lines, source=self.text)

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


#: Directories (repo-relative) the analyzer walks. Rule modules narrow
#: further via path predicates (e.g. blocking-call scans the package only).
DEFAULT_SCAN_DIRS = ("matchmaking_tpu", "scripts", "tests")
DEFAULT_SCAN_FILES = ("bench.py",)
_SKIP_PARTS = {"__pycache__", ".git"}


def discover(root: str) -> list[SourceFile]:
    out: list[SourceFile] = []
    for rel in DEFAULT_SCAN_FILES:
        if os.path.isfile(os.path.join(root, rel)):
            out.append(SourceFile(root, rel))
    for base in DEFAULT_SCAN_DIRS:
        top = os.path.join(root, base)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_PARTS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, name), root)
                    out.append(SourceFile(root, rel))
    return out


def in_package(sf: SourceFile) -> bool:
    return sf.path.startswith("matchmaking_tpu/") and not sf.path.startswith(
        "matchmaking_tpu/analysis/")


def qualname_of(stack: Iterable[ast.AST]) -> str:
    """``Class.method`` context from an enclosing-node stack."""
    parts = [
        node.name for node in stack
        if isinstance(node, (ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef))
    ]
    return ".".join(parts) if parts else "<module>"


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains ('' when not a plain chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def apply_ignores(findings: list[Finding],
                  sources: dict[str, SourceFile]) -> list[Finding]:
    """Drop findings suppressed by an (active, reasoned) inline ignore."""
    kept = []
    for f in findings:
        sf = sources.get(f.path)
        if sf is not None and sf.ignores.suppressed(f.line, f.rule):
            continue
        kept.append(f)
    return kept


def stale_ignores(sources: "Iterable[SourceFile]") -> list[Finding]:
    """Suppression hygiene: every ACTIVE ignore that suppressed nothing in
    this (full-rules) run is itself a finding.  Call after apply_ignores —
    usage marks accumulate there."""
    out: list[Finding] = []
    for sf in sources:
        for comment_line, rules in sf.ignores.entries:
            dead = [r for r in sorted(rules)
                    if r != "stale-ignore"
                    and (comment_line, r) not in sf.ignores.used]
            if dead:
                out.append(Finding(
                    "stale-ignore", sf.path, comment_line,
                    f"ignore[{','.join(dead)}] no longer suppresses any "
                    f"finding — the violation it excused is gone; delete "
                    f"the comment (dead ignores silently hide FUTURE "
                    f"findings on this line)",
                    f"ignore@{comment_line}"))
    return out


# ---- baseline --------------------------------------------------------------

def load_baseline(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("findings", []))


def write_baseline(path: str, findings: list[Finding]) -> None:
    entries = [
        {"rule": f.rule, "path": f.path, "context": f.context,
         "reason": "TODO: document why this finding is accepted"}
        for f in sorted(set(findings),
                        key=lambda f: (f.path, f.rule, f.context))
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"findings": entries}, f, indent=2, sort_keys=True)
        f.write("\n")


def update_baseline(path: str, findings: list[Finding]) -> tuple[int, int]:
    """Rewrite the baseline IN PLACE: drop entries no current finding
    matches (their violations are fixed), keep matching entries with their
    hand-written reasons verbatim.  Returns (kept, dropped)."""
    baseline = load_baseline(path)
    current = {f.fingerprint() for f in findings}
    kept = [e for e in baseline
            if (e.get("rule", ""), e.get("path", ""),
                e.get("context", "")) in current]
    dropped = len(baseline) - len(kept)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"findings": kept}, f, indent=2, sort_keys=True)
        f.write("\n")
    return len(kept), dropped


def split_by_baseline(
    findings: list[Finding], baseline: list[dict]
) -> tuple[list[Finding], list[Finding]]:
    """(new, accepted): a finding is accepted when a baseline entry matches
    its (rule, path, context) fingerprint."""
    accepted_keys = {(e.get("rule", ""), e.get("path", ""),
                      e.get("context", "")) for e in baseline}
    new, accepted = [], []
    for f in findings:
        (accepted if f.fingerprint() in accepted_keys else new).append(f)
    return new, accepted


def repo_root() -> str:
    """The repo the analyzer should scan: cwd when it holds the package,
    else the checkout this module was imported from."""
    cwd = os.getcwd()
    if os.path.isdir(os.path.join(cwd, "matchmaking_tpu")):
        return cwd
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
