"""Metrics/observability: counters, latency percentiles, stage spans.

The reference leans on Elixir ``Logger`` and BEAM introspection; the rebuild
makes the BASELINE headline numbers (matches/sec, p50/p99 end-to-end latency,
pool occupancy, batch fill, recompile count) first-class (SURVEY.md §5
"Metrics/logging/observability"). Pure stdlib, no deps.
"""

from __future__ import annotations

import bisect
import json
import math
import threading
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field


class Counter:
    def __init__(self) -> None:
        self._values: dict[str, float] = defaultdict(float)

    def inc(self, name: str, value: float = 1.0) -> None:
        self._values[name] += value

    def get(self, name: str) -> float:
        return self._values[name]

    def snapshot(self) -> dict[str, float]:
        return dict(self._values)


class LatencyRecorder:
    """Sliding-window latency recorder: keeps the most recent ``window``
    samples (bounded memory for a long-lived service; one sample lands here
    per matched player) plus lifetime count/max; percentiles are over the
    window."""

    def __init__(self, window: int = 65_536) -> None:
        self._samples: deque[float] = deque(maxlen=window)
        self._count = 0
        self._max = 0.0

    def record(self, seconds: float) -> None:
        self._samples.append(seconds)
        self._count += 1
        if seconds > self._max:
            self._max = seconds

    def __len__(self) -> int:
        return self._count

    @staticmethod
    def _pct(sorted_samples: list[float], p: float) -> float:
        """Nearest-rank percentile over an ALREADY-sorted sample list — the
        one percentile definition, shared by ``percentile`` and
        ``summary_ms`` so the two can never drift apart."""
        n = len(sorted_samples)
        k = min(n - 1, max(0, math.ceil(p / 100.0 * n) - 1))
        return sorted_samples[k]

    def percentile(self, p: float) -> float:
        if not self._samples:
            return math.nan
        return self._pct(sorted(self._samples), p)

    def summary_ms(self) -> dict[str, float]:
        if not self._samples:
            return {"count": 0}
        # ONE sorted pass per scrape; every percentile reads from it.
        s = sorted(self._samples)
        return {
            "count": self._count,
            "p50_ms": round(self._pct(s, 50) * 1e3, 3),
            "p90_ms": round(self._pct(s, 90) * 1e3, 3),
            "p99_ms": round(self._pct(s, 99) * 1e3, 3),
            "max_ms": round(self._max * 1e3, 3),
            "mean_ms": round(sum(s) / len(s) * 1e3, 3),
        }


#: Default per-stage latency buckets: log-spaced (factor 2) upper bounds
#: from 100 µs to ~14 min. Wide enough that one histogram scheme covers
#: sub-millisecond host stages (pack/H2D) AND long low-traffic match waits
#: (the e2e stage must not saturate into +Inf while the LatencyRecorder
#: still resolves, or the p99 cross-check diverges); factor 2 bounds the
#: p99-from-buckets error at one octave.
DEFAULT_STAGE_BUCKETS: tuple[float, ...] = tuple(
    1e-4 * 2.0 ** k for k in range(24))


class Histogram:
    """Fixed-bucket latency histogram (Prometheus-style cumulative ``le``
    semantics at export; stored as per-bucket counts here). Replaces the
    averages-only span reporting in the /metrics path: an average cannot
    show the bimodal batcher-wait or H2D-stall signatures that explain a
    p99 outlier."""

    __slots__ = ("buckets", "counts", "overflow", "count", "sum")

    def __init__(self, buckets: tuple[float, ...] = DEFAULT_STAGE_BUCKETS):
        # Sorted is a bisect precondition AND a prom-exposition requirement
        # (le labels must ascend) — user-supplied stage_buckets get no
        # ordering promise, so enforce it here.
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * len(self.buckets)
        self.overflow = 0  # observations above the last bucket (+Inf)
        self.count = 0
        self.sum = 0.0

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.sum += seconds
        i = bisect.bisect_left(self.buckets, seconds)
        if i < len(self.buckets):
            self.counts[i] += 1
        else:
            self.overflow += 1

    def percentile(self, p: float) -> float:
        """Upper edge of the bucket holding the p-th percentile (NaN when
        empty; the last finite edge when it lands in +Inf) — accurate to
        one bucket width by construction."""
        if self.count == 0:
            return math.nan
        rank = max(1, math.ceil(p / 100.0 * self.count))
        cum = 0
        for edge, c in zip(self.buckets, self.counts):
            cum += c
            if cum >= rank:
                return edge
        return self.buckets[-1] if self.buckets else math.nan

    def to_dict(self) -> dict:
        """JSON-ready: cumulative bucket counts keyed by stringified upper
        bound (prom ``le`` semantics), plus count/sum."""
        cum = 0
        le: dict[str, int] = {}
        for edge, c in zip(self.buckets, self.counts):
            cum += c
            le[format(edge, ".6g")] = cum
        le["+Inf"] = cum + self.overflow
        return {"le": le, "count": self.count, "sum_s": round(self.sum, 6)}


@dataclass
class Span:
    """Wall-clock span for per-stage latency accounting (batcher wait, H2D,
    kernel, D2H, publish — SURVEY.md §5 tracing plan)."""

    name: str
    start: float = field(default_factory=time.perf_counter)
    elapsed: float = 0.0

    def stop(self) -> float:
        self.elapsed = time.perf_counter() - self.start
        return self.elapsed


class CompileCounter:
    """Process-wide XLA compilation counter (SURVEY.md §5 names "recompile
    count" explicitly). The whole p99 story rests on bucketed static shapes —
    a config typo that un-buckets one queue would silently add multi-hundred-
    ms compiles to the hot path; this makes that visible in /metrics and
    assertable in tests (soak asserts zero after warmup).

    Counts ``/jax/core/compile/backend_compile_duration`` events via
    jax.monitoring — one per actual XLA backend compile (cache hits don't
    fire it). Process-wide by nature (the monitoring hook is global), which
    matches the hazard: ANY unexpected compile in the serving process is a
    latency cliff."""

    _registered = False
    _count = 0
    _seconds = 0.0
    # The monitoring listener fires on whichever thread runs the compile
    # (dispatch happens from service worker threads via to_thread), and
    # count+seconds must move together — guard the read-modify-write.
    _lock = threading.Lock()

    @classmethod
    def install(cls) -> None:
        if cls._registered:
            return
        try:
            import jax.monitoring as mon
        except Exception:  # pragma: no cover - jax always present in practice
            return

        def on_event(name: str, duration: float, **kw) -> None:
            if name == "/jax/core/compile/backend_compile_duration":
                with cls._lock:
                    cls._count += 1
                    cls._seconds += duration

        mon.register_event_duration_secs_listener(on_event)
        cls._registered = True

    @classmethod
    def count(cls) -> int:
        return cls._count

    @classmethod
    def seconds(cls) -> float:
        """Total backend-compile wall time — a recompile COUNT says the
        cliff exists; the duration says how much p99 budget it burned."""
        return cls._seconds


class Metrics:
    def __init__(self, stage_buckets: tuple[float, ...] | None = None) -> None:
        self.counters = Counter()
        self.latency: dict[str, LatencyRecorder] = defaultdict(LatencyRecorder)
        #: Point-in-time gauges (set, not accumulated): circuit-breaker
        #: state per queue (0=closed 1=half_open 2=open), time degraded,
        #: current probe backoff — anything whose CURRENT value matters
        #: more than its history.
        self.gauges: dict[str, float] = {}
        #: True per-stage latency histograms, fed by the flight recorder
        #: (utils/trace.py) on every settled trace: queue → stage →
        #: Histogram. Exported as ONE Prometheus histogram family,
        #: ``matchmaking_stage_seconds{queue=...,stage=...}``.
        self.stage_buckets = tuple(stage_buckets or DEFAULT_STAGE_BUCKETS)
        self.stages: dict[str, dict[str, Histogram]] = {}
        # No CompileCounter.install() here: installing imports jax, which a
        # pure-CPU deployment (CpuEngine = numpy oracle) otherwise never
        # pays for. TpuEngine.__init__ installs it — exactly the processes
        # where a compile can happen; count() reads 0 elsewhere.

    def record_latency(self, name: str, seconds: float) -> None:
        self.latency[name].record(seconds)

    def observe_stage(self, queue: str, stage: str, seconds: float) -> None:
        per_q = self.stages.get(queue)
        if per_q is None:
            per_q = self.stages[queue] = {}
        hist = per_q.get(stage)
        if hist is None:
            hist = per_q[stage] = Histogram(self.stage_buckets)
        hist.observe(seconds)

    def set_gauge(self, name: str, value: float) -> None:
        self.gauges[name] = float(value)

    def report(self) -> dict:
        counters = self.counters.snapshot()
        counters["xla_compiles"] = float(CompileCounter.count())
        counters["xla_compile_seconds"] = round(CompileCounter.seconds(), 6)
        return {
            "counters": counters,
            "gauges": dict(self.gauges),
            "latency": {k: v.summary_ms() for k, v in self.latency.items()},
            "stage_seconds": {
                q: {s: h.to_dict() for s, h in per_q.items()}
                for q, per_q in self.stages.items()
            },
        }

    def report_json(self) -> str:
        return json.dumps(self.report(), sort_keys=True)
