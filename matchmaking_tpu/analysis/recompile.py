"""``recompile``: the static/trace-time gate behind tests/test_recompiles.py.

A hot-path XLA recompile is a multi-hundred-ms p99 cliff; the engine's
whole shape discipline (batch buckets, packed rows, two step variants)
exists to prevent one. Two hazards this rule catches before a soak does:

- **jaxpr drift** — a jitted kernel whose trace depends on mutable Python
  state (a module counter, a rebound closure scalar, wall clock read at
  trace time): two traces under the SAME canonical config and the SAME
  input shapes must produce byte-identical jaxprs. Drift means either a
  recompile per invocation (if the varying value reaches the cache key)
  or — worse — a silently frozen stale value baked into the executable.
- **Python-scalar closure captures** — a function handed to ``jax.jit``
  that closes over a loop variable or a rebound local: the classic
  late-binding bug (`for k: fns.append(jit(lambda x: x * k))`) traces
  every entry with the LAST k. Detected statically over the kernel
  modules.

The dynamic half builds each kernel family under a small canonical config
(CPU backend, trace only — nothing executes) and compares
``jax.make_jaxpr`` output across two value-varied, shape-identical
invocations.
"""

from __future__ import annotations

import ast

from matchmaking_tpu.analysis.core import Finding, SourceFile, dotted_name

RULE = "recompile"

#: Modules whose jit sites get the static closure-capture scan.
KERNEL_MODULES = (
    "matchmaking_tpu/engine/kernels.py",
    "matchmaking_tpu/engine/role_kernels.py",
    "matchmaking_tpu/engine/pallas_kernels.py",
    "matchmaking_tpu/engine/teams.py",
    "matchmaking_tpu/engine/sharded.py",
)


# ---- static: closure captures ----------------------------------------------

def _is_jit_expr(node: ast.AST) -> bool:
    """True for ``jax.jit`` / ``jit`` / ``(functools.)partial(jax.jit, ...)``."""
    name = dotted_name(node)
    if name in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call) and dotted_name(node.func).endswith(
            "partial") and node.args:
        return _is_jit_expr(node.args[0])
    return False


def _bound_names(fn: ast.AST) -> set[str]:
    bound: set[str] = set()
    args = fn.args
    for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
    return bound


def _loaded_names(fn: ast.AST) -> set[str]:
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    loads: set[str] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                loads.add(node.id)
    return loads


def _module_level_names(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.add(sub.name)
                break  # don't descend into bodies for module names
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                            ast.Store):
                    names.add(sub.id)
    return names


class _JitSiteScanner(ast.NodeVisitor):
    """Finds jitted functions and checks their free variables against the
    enclosing function scopes for loop targets / multiple rebinds."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.findings: list[Finding] = []
        self._module_names = _module_level_names(sf.tree)
        self._fn_stack: list[ast.AST] = []

    def _check_captures(self, fn: ast.AST, site_line: int,
                        label: str) -> None:
        import builtins

        free = (_loaded_names(fn) - _bound_names(fn) - self._module_names
                - set(dir(builtins)) - {"self"})
        if not free:
            return
        for name in sorted(free):
            hazard = self._capture_hazard(name)
            if hazard:
                self.findings.append(Finding(
                    RULE, self.sf.path, site_line,
                    f"jitted {label} captures Python variable {name!r} "
                    f"{hazard}: bind it via functools.partial / a default "
                    f"arg, or pass it as a traced argument",
                    label))

    def _capture_hazard(self, name: str) -> str | None:
        """Why capturing ``name`` from an enclosing scope is dangerous
        (None when it's bound exactly once — effectively a constant)."""
        for fn in reversed(self._fn_stack):
            binds = 0
            for node in ast.walk(fn):
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    for t in ast.walk(node.target):
                        if isinstance(t, ast.Name) and t.id == name:
                            return "bound by a for-loop (late binding: " \
                                   "every trace sees the LAST value)"
                if isinstance(node, ast.AugAssign) and isinstance(
                        node.target, ast.Name) and node.target.id == name:
                    return "mutated with augmented assignment in the " \
                           "enclosing scope"
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        for t in ast.walk(tgt):
                            if isinstance(t, ast.Name) and t.id == name:
                                binds += 1
            if binds > 1:
                return "rebound more than once in the enclosing scope"
            if binds == 1 or name in {
                a.arg for a in (*fn.args.posonlyargs, *fn.args.args,
                                *fn.args.kwonlyargs)}:
                return None  # bound once here: a per-factory constant
        return None

    def _enter_fn(self, node):
        for deco in getattr(node, "decorator_list", ()):
            if _is_jit_expr(deco):
                self._check_captures(node, node.lineno, node.name)
        self._fn_stack.append(node)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _enter_fn
    visit_AsyncFunctionDef = _enter_fn

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit_expr(node.func) and node.args:
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                self._check_captures(target, node.lineno,
                                     f"lambda@{node.lineno}")
            # jit(name)/jit(self._method): nothing lexical to scan here —
            # the def site is scanned when its decorators are walked.
        self.generic_visit(node)


def check_static(sources: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in sources:
        if sf.path in KERNEL_MODULES:
            v = _JitSiteScanner(sf)
            v.visit(sf.tree)
            findings.extend(v.findings)
    return findings


# ---- dynamic: jaxpr drift under canonical configs --------------------------

def _canonical_pool(ks, variant: int):
    import jax.numpy as jnp
    import numpy as np

    from matchmaking_tpu.core.pool import PlayerPool

    init = PlayerPool.empty_device_arrays(ks.capacity)
    for name, dt in getattr(ks, "extra_pool_fields", {}).items():
        init[name] = np.zeros(ks.capacity, dt)
    rng = np.random.default_rng(101 + variant)
    n = max(1, ks.capacity // 2)
    for col, vals in (
        ("rating", rng.normal(1500, 150, n)),
        ("rd", rng.uniform(30, 200, n)),
        ("threshold", np.full(n, 90.0 + variant)),
        ("enqueue_t", rng.uniform(0, 3, n)),
    ):
        if col in init:
            init[col][:n] = vals.astype(init[col].dtype)
    if "active" in init:
        init["active"][:n] = True
    return {k: jnp.asarray(v) for k, v in init.items()}


def _canonical_packed(ks, b: int, variant: int):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(33 + variant)
    rows = 9 + (1 if getattr(ks, "is_role", False) else 0)
    packed = rng.uniform(0.0, 1.0, (rows, b)).astype(np.float32)
    packed[0] = ks.capacity  # slot row: all padding lanes
    return jnp.asarray(packed)


def _trace_once(fn, args) -> str:
    """One FRESH trace of ``fn``. jax caches traces on (callable, avals) —
    both inside jit wrappers and inside make_jaxpr itself — so a naive
    second make_jaxpr returns the FIRST trace and drift is structurally
    invisible. Unwrap the jit wrapper to the raw Python callable and trace
    it through a fresh lambda per invocation (distinct callable identity →
    cache miss → the Python body actually re-runs)."""
    import jax

    raw = getattr(fn, "__wrapped__", fn)
    return str(jax.make_jaxpr(lambda *a: raw(*a))(*args))


def _drift(fn, make_args, name: str, context: str,
           findings: list[Finding]) -> None:
    try:
        j0 = _trace_once(fn, make_args(0))
        j1 = _trace_once(fn, make_args(1))
    except Exception as e:  # tracing itself failed: surface, don't crash
        findings.append(Finding(
            RULE, context, 0,
            f"could not trace {name}: {type(e).__name__}: {e}", name))
        return
    if j0 != j1:
        findings.append(Finding(
            RULE, context, 0,
            f"jaxpr drift in {name}: two same-shape traces under the "
            f"canonical config differ — the kernel's trace depends on "
            f"mutable Python state (recompile or stale-constant hazard)",
            name))


def check_dynamic() -> list[Finding]:
    """Trace every kernel family twice under canonical small configs and
    compare jaxprs. Trace-only — nothing executes, so whatever backend the
    host process configured is fine (the CLI pins CPU for itself in
    engine.main; pytest gets conftest's CPU mesh). No process-global
    state is mutated here: the lint node runs inside tier-1, and flipping
    JAX_PLATFORMS mid-suite would silently re-platform every later test."""
    findings: list[Finding] = []

    from matchmaking_tpu.engine.kernels import kernel_set

    for label, kwargs in (
        ("1v1", dict(glicko2=False, widen_per_sec=5.0)),
        ("1v1-glicko2", dict(glicko2=True, widen_per_sec=0.0)),
    ):
        ks = kernel_set(capacity=64, top_k=4, pool_block=32,
                        max_threshold=400.0, pair_rounds=4, **kwargs)
        ctx = "matchmaking_tpu/engine/kernels.py"
        b = 16
        for name in ("search_step_packed", "search_step_packed_nofilter",
                     "search_step_packed_rescan", "admit_packed"):
            fn = getattr(ks, name, None)
            if fn is None:
                continue
            _drift(fn,
                   lambda v: (_canonical_pool(ks, v),
                              _canonical_packed(ks, b, v)),
                   f"kernels.{label}.{name}", ctx, findings)
        evict = getattr(ks, "evict", None)
        if evict is not None:
            import jax.numpy as jnp
            import numpy as np

            def evict_args(v, ks=ks):
                ev = np.full(ks.evict_bucket, ks.capacity, np.int32)
                ev[0] = v  # vary content, not shape
                return (_canonical_pool(ks, v), jnp.asarray(ev))

            _drift(evict, evict_args, f"kernels.{label}.evict", ctx,
                   findings)

    from matchmaking_tpu.engine.role_kernels import role_kernel_set

    rks = role_kernel_set(capacity=32, team_size=2,
                          role_slots=("tank", "dps"), widen_per_sec=5.0,
                          max_threshold=400.0, max_matches=8, rounds=4)
    ctx = "matchmaking_tpu/engine/role_kernels.py"
    for name in ("search_step_packed", "admit_packed"):
        fn = getattr(rks, name, None)
        if fn is None:
            continue
        _drift(fn,
               lambda v: (_canonical_pool(rks, v),
                          _canonical_packed(rks, 16, v)),
               f"role_kernels.{name}", ctx, findings)

    try:
        from matchmaking_tpu.engine.pallas_kernels import (
            pack_batch_rows,
            pack_pool_rows,
            pallas_block_best,
        )
    except ImportError:
        return findings  # pallas unavailable in this build: skip, not fail

    import functools

    import jax.numpy as jnp
    import numpy as np

    P, B = 1024, 64
    pb = functools.partial(
        pallas_block_best, super_blk=256, sub_blk=2048, b_tile=256,
        capacity=P, glicko2=False, widen_per_sec=5.0, max_threshold=400.0,
        interpret=True)

    def pallas_args(v):
        from matchmaking_tpu.core.pool import PlayerPool

        rng = np.random.default_rng(55 + v)
        arrs = PlayerPool.empty_device_arrays(P)
        n = P // 2
        arrs["rating"][:n] = rng.normal(1500, 200, n).astype(np.float32)
        arrs["rd"][:n] = rng.uniform(30, 200, n).astype(np.float32)
        arrs["threshold"][:n] = 100.0 + v
        arrs["active"][:n] = True
        pool = {k: jnp.asarray(x) for k, x in arrs.items()}
        batch = {
            "slot": jnp.asarray(np.arange(B, dtype=np.int32)),
            "rating": jnp.asarray(
                rng.normal(1500, 200, B).astype(np.float32)),
            "rd": jnp.asarray(rng.uniform(30, 200, B).astype(np.float32)),
            "region": jnp.zeros(B, jnp.int32),
            "mode": jnp.zeros(B, jnp.int32),
            "threshold": jnp.full(B, 100.0, jnp.float32),
            "enqueue_t": jnp.asarray(
                rng.uniform(0, 3, B).astype(np.float32)),
            "valid": jnp.ones(B, bool),
        }
        q_thr_eff = jnp.full(B, 100.0 + v, jnp.float32)
        return (pack_pool_rows(pool), pack_batch_rows(batch, q_thr_eff),
                float(1.5))

    _drift(pb, pallas_args, "pallas_block_best",
           "matchmaking_tpu/engine/pallas_kernels.py", findings)
    return findings


def check(sources: list[SourceFile],
          dynamic: bool = True) -> list[Finding]:
    findings = check_static(sources)
    if dynamic:
        findings.extend(check_dynamic())
    return findings
