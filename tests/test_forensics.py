"""Incident-forensics suite (ISSUE 18).

- **EventSpine**: one process-wide monotone seq under concurrent
  worker-thread emitters (ring order IS seq order), the deterministic
  ``transcript()`` projection (clock fields and timing-valued refs
  dropped, counter refs kept) with a stable digest, and observers
  running OUTSIDE the spine lock (a slow capture must not block other
  threads' emissions).
- **EventLog**: appends are spine-stamped (seq + mono_ns + component)
  and ``snapshot()`` orders by seq, not wall clock — two events in the
  same millisecond render in causal order.
- **IncidentRecorder**: trigger rules fire a bundle, the per-class rate
  limiter and the reentrancy guard COUNT their drops, and
  ``validate_bundle`` accepts every captured bundle while rejecting the
  schema mutations an operator could plausibly produce by hand.
- **HTTP surfaces mid-failover**: after a real crash → lease-expiry
  takeover → successor adoption, concurrent ``/debug/incidents`` +
  ``/metrics?format=prom`` scrapes stay spec-valid (parse_prom) while
  the listing's trigger seqs stay monotone and the per-id fetch returns
  a bundle that validates.
- **Drain non-interference**: a capture fired mid-load neither blocks
  the drain (``fully_drained`` settles) nor leaks a settlement credit
  (``debug_invariants`` twin active, every published player matches).
- **Offline analyzer**: ``scripts/postmortem.py`` reconstructs the
  takeover root chain (lease expiry → epoch bump → replay window →
  takeover → burn → burn clear) from a synthetic bundle alone, and
  ``scripts/journal_dump.py --lsn-range`` slices exactly the WAL window
  a bundle's journal watermark names.
"""

import asyncio
import importlib.util
import io
import json
import os
import threading
import time

import pytest

from matchmaking_tpu.config import (
    BatcherConfig,
    Config,
    DurabilityConfig,
    EngineConfig,
    ForensicsConfig,
    QueueConfig,
    ReplicationConfig,
)
from matchmaking_tpu.service.app import MatchmakingApp
from matchmaking_tpu.service.broker import Properties
from matchmaking_tpu.service.replication import ReplicationHub
from matchmaking_tpu.testing.drain import fully_drained
from matchmaking_tpu.utils.forensics import (
    DETERMINISTIC_KINDS,
    INCIDENT_SCHEMA,
    EventSpine,
    component_of,
    validate_bundle,
)
from matchmaking_tpu.utils.trace import EventLog

pytestmark = pytest.mark.forensics

Q = "matchmaking.search"
_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")


def _load_script(name: str):
    spec = importlib.util.spec_from_file_location(
        f"mm_script_{name}", os.path.join(_SCRIPTS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _cfg(**kw) -> Config:
    base = dict(
        queues=(QueueConfig(rating_threshold=50.0, dedup_ttl_s=600.0),),
        engine=EngineConfig(backend="tpu", pool_capacity=256, pool_block=64,
                            batch_buckets=(8, 32), top_k=4),
        batcher=BatcherConfig(max_batch=8, max_wait_ms=5.0),
    )
    base.update(kw)
    return Config(**base)


def _publish(app, pid, rating, reply_q):
    app.broker.publish(
        Q, json.dumps({"id": pid, "rating": rating}).encode(),
        Properties(reply_to=reply_q, correlation_id=pid))


async def _quiesce(app, rt, *, matched_at_least=0, standby=None,
                   replication=True, tries=2400):
    for _ in range(tries):
        await asyncio.sleep(0.025)
        if standby is not None:
            standby.pump()
        if fully_drained(app, rt, Q, matched_at_least,
                         replication=replication):
            return True
    return False


# ---- event spine ------------------------------------------------------------


def test_spine_seq_monotone_under_concurrent_threads():
    """Four worker threads stamping concurrently: every seq is unique,
    the ring's iteration order is seq order (the draw + append happen as
    one step under the lock), and the window() slice stays sorted."""
    spine = EventSpine(ring=4096)
    n_threads, per_thread = 4, 200
    start = threading.Barrier(n_threads)

    def emit(tid: int) -> None:
        start.wait()
        for i in range(per_thread):
            spine.stamp("engine_crash", queue=f"q{tid}", detail=str(i))

    threads = [threading.Thread(target=emit, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rows = spine.window()
    assert len(rows) == n_threads * per_thread
    seqs = [ev["seq"] for ev in rows]
    assert seqs == sorted(seqs)
    assert len(set(seqs)) == len(seqs)
    assert seqs[0] == 1 and seqs[-1] == n_threads * per_thread
    # Ring order IS seq order even before window() sorts.
    raw = [ev["seq"] for ev in spine._ring]
    assert raw == sorted(raw)


def test_spine_transcript_drops_clocks_and_keeps_counter_refs():
    """The deterministic projection: clock fields (mono_ns, wall) and
    timing-valued refs (rto_ms) never reach the transcript; counter refs
    (epoch, players) do. Two spines stamped with the same script but
    different wall clocks digest identically."""
    def script(spine: EventSpine) -> None:
        spine.stamp("lease_expired", Q, refs={"epoch": 1})
        spine.stamp("epoch_bump", Q, refs={"epoch": 2, "prev_epoch": 1})
        spine.stamp("replay_window", Q, refs={"epoch": 2, "players": 6,
                                              "records": 96})
        spine.stamp("failover_takeover", Q,
                    refs={"epoch": 2, "players": 6, "rto_ms": 7.31})
        # Non-deterministic kind: on the spine, out of the transcript.
        spine.stamp("slo_burn", Q, refs={"burn_fast": 100.0})

    a, b = EventSpine(), EventSpine()
    script(a)
    time.sleep(0.01)  # different wall/mono values on b, same script
    script(b)
    ta = a.transcript()
    assert [r["kind"] for r in ta] == ["lease_expired", "epoch_bump",
                                      "replay_window", "failover_takeover"]
    takeover = ta[-1]
    assert takeover["refs"] == {"epoch": 2, "players": 6}  # rto_ms dropped
    assert all("mono_ns" not in r and "wall" not in r and "seq" not in r
               for r in ta)
    assert a.digest() == b.digest()
    assert set(DETERMINISTIC_KINDS) >= {r["kind"] for r in ta}


def test_spine_observers_run_outside_the_lock():
    """A slow observer (a capture in flight) must not hold the spine
    lock: another thread's stamp during the observer's sleep returns
    promptly instead of queueing behind the capture."""
    spine = EventSpine()
    in_observer = threading.Event()
    release = threading.Event()

    def slow_observer(ev):
        if ev["kind"] == "breaker_trip":
            in_observer.set()
            release.wait(timeout=5.0)

    spine.subscribe(slow_observer)
    t = threading.Thread(target=spine.stamp, args=("breaker_trip", Q))
    t.start()
    assert in_observer.wait(timeout=5.0)
    t0 = time.monotonic()
    spine.stamp("engine_revive", Q)  # must not block on the observer
    elapsed = time.monotonic() - t0
    release.set()
    t.join()
    assert elapsed < 1.0, (
        f"stamp blocked {elapsed:.3f}s behind a slow observer — the "
        f"capture is holding the spine lock")
    assert [ev["seq"] for ev in spine.window()] == [1, 2]


def test_event_log_snapshot_orders_by_seq_not_wall_clock():
    """Rows appended with DESCENDING wall stamps still snapshot in seq
    (causal) order, and every row carries seq/mono_ns/component."""
    log = EventLog(64)
    now = time.time()
    # Stamp with explicit wall going backwards (clock step / NTP skew).
    log.spine.stamp("breaker_trip", Q, wall=now + 5.0)
    log._events.append(log.spine._ring[-1])
    log.append("engine_revive", Q)
    rows = log.spine.window()
    assert [r["seq"] for r in rows] == [1, 2]
    assert rows[0]["wall"] > rows[1]["wall"]  # wall order is inverted...
    snap = EventLog(64)
    a = snap.append("breaker_trip", Q)
    b = snap.append("engine_revive", Q, component="engine")
    listed = snap.snapshot()
    assert [r["seq"] for r in listed] == [a["seq"], b["seq"]]
    assert listed[0]["component"] == "service"  # component_of fallback
    assert listed[1]["component"] == "engine"   # explicit wins
    assert all("mono_ns" in r for r in listed)


def test_component_table_routes_known_kinds():
    assert component_of("journal_compacted") == "durability"
    assert component_of("failover_takeover") == "replication"
    assert component_of("autotune_applied") == "control"
    assert component_of("slo_burn") == "slo"
    assert component_of("breaker_trip") == "service"
    assert component_of("spec_invalidate") == "engine"
    assert component_of("totally_unknown") == "service"


# ---- recorder: triggers, rate limit, reentrancy -----------------------------


async def test_recorder_trigger_rate_limit_and_reentrancy():
    """One breaker_trip fires a capture; a second within min_interval_s
    is dropped AND counted; a trigger observed while a capture is in
    flight (self-amplification) is dropped AND counted; every captured
    bundle validates clean."""
    app = MatchmakingApp(_cfg(
        forensics=ForensicsConfig(min_interval_s=60.0)))
    await app.start()
    try:
        ev = app.events.append("breaker_trip", Q, "fixture trip",
                               refs={"crashes": 2})
        assert app.incidents.captured == 1
        assert app.incidents.by_class == {"breaker_trip": 1}
        assert app.incidents.dropped == 0
        bundle = app.incidents.get("inc-000001")
        assert bundle is not None
        assert bundle["trigger"]["seq"] == ev["seq"]
        assert bundle["schema"] == INCIDENT_SCHEMA
        assert validate_bundle(bundle) == []
        assert app.metrics.counters.get("incidents_captured") == 1

        # Rate limit: same class inside min_interval_s → counted drop.
        app.events.append("breaker_trip", Q, "storm repeat")
        assert app.incidents.captured == 1
        assert app.incidents.dropped == 1
        assert app.metrics.counters.get("incidents_dropped") == 1

        # Reentrancy: a trigger while a capture is in flight is the
        # self-amplification case — dropped and counted, never recursed.
        app.incidents._capturing = True
        try:
            app.events.append("crash_recovered", Q, "mid-capture")
        finally:
            app.incidents._capturing = False
        assert app.incidents.captured == 1
        assert app.incidents.dropped == 2

        # A different class is NOT rate-limited by breaker_trip's stamp.
        app.events.append("crash_recovered", Q, "other class")
        assert app.incidents.by_class.get("crash_recovery") == 1
        assert app.incidents.dropped == 2
    finally:
        await app.stop()


async def test_capture_persist_retention_and_snapshot(tmp_path):
    """Bundles persist under incident_dir with the retention cap pruning
    oldest-first; snapshot() reports counters + capture p99."""
    inc_dir = str(tmp_path / "incidents")
    app = MatchmakingApp(_cfg(
        forensics=ForensicsConfig(incident_dir=inc_dir, min_interval_s=0.0,
                                  retention_files=2)))
    await app.start()
    try:
        for i in range(3):
            app.events.append("breaker_trip", Q, f"trip {i}")
        files = sorted(os.listdir(inc_dir))
        assert files == ["incident_inc-000002_breaker_trip.json",
                         "incident_inc-000003_breaker_trip.json"]
        with open(os.path.join(inc_dir, files[-1]), encoding="utf-8") as f:
            assert validate_bundle(json.load(f)) == []
        snap = app.incidents.snapshot()
        assert snap["captured"] == 3 and snap["dropped"] == 0
        assert snap["by_class"] == {"breaker_trip": 3}
        assert snap["capture_ms_p99"] is not None
        assert [b["id"] for b in snap["incidents"]] == [
            "inc-000001", "inc-000002", "inc-000003"]
    finally:
        await app.stop()


def test_validate_bundle_rejects_schema_mutations():
    ok = {
        "schema": INCIDENT_SCHEMA, "id": "inc-000001",
        "trigger": {"class": "failover", "seq": 5, "kind":
                    "failover_takeover", "queue": Q, "detail": "", "refs": {}},
        "captured_wall": 1.0, "capture_ms": 0.5,
        "spine": [{"seq": 1, "mono_ns": 10, "wall": 1.0,
                   "component": "replication", "queue": Q,
                   "kind": "lease_expired", "refs": {}},
                  {"seq": 5, "mono_ns": 20, "wall": 1.0,
                   "component": "replication", "queue": Q,
                   "kind": "failover_takeover", "refs": {}}],
        "spine_digest": "x", "telemetry": {}, "replication": {},
        "journal": {}, "counters": {},
    }
    assert validate_bundle(ok) == []
    assert validate_bundle([]) != []
    assert any("schema" in p for p in validate_bundle(
        {**ok, "schema": "mm.incident/999"}))
    missing = dict(ok)
    del missing["spine_digest"]
    assert any("spine_digest" in p for p in validate_bundle(missing))
    assert any("trigger class" in p for p in validate_bundle(
        {**ok, "trigger": {**ok["trigger"], "class": "nope"}}))
    broken = {**ok, "spine": list(reversed(ok["spine"]))}
    assert any("strictly increasing" in p for p in validate_bundle(broken))
    assert any("capture_ms" in p for p in validate_bundle(
        {**ok, "capture_ms": "fast"}))


# ---- HTTP surfaces mid-failover ---------------------------------------------


async def test_debug_incidents_and_prom_concurrent_after_failover(tmp_path):
    """Crash → lease-expiry takeover → successor adoption, then
    CONCURRENT /debug/incidents + /metrics?format=prom scrapes while
    load flows: prom stays spec-valid with the incident families
    present, the incident listing's trigger seqs are monotone, and the
    per-id fetch returns a bundle that validates."""
    import aiohttp

    from test_observability import parse_prom

    port = 19271
    hub = ReplicationHub(lease_s=0.4)
    app = MatchmakingApp(_cfg(
        durability=DurabilityConfig(journal_dir=str(tmp_path / "h0"),
                                    fsync="window"),
        replication=ReplicationConfig(role="primary", owner="hostA")),
        replication_hub=hub)
    reply = "forensics.replies"
    app.broker.declare_queue(reply)
    app.broker.basic_consume(reply, lambda d: None, prefetch=1_000_000)
    await app.start()
    rt = app.runtime(Q)
    standby = hub.standby(Q, owner="hostB")
    for i in range(4):
        _publish(app, f"fp{i}", 1500.0 + (i // 2) * 400.0, reply)
    assert await _quiesce(app, rt, matched_at_least=4, standby=standby)
    await app.crash()
    standby.takeover(time.monotonic() + 0.4 + 0.05)

    app2 = MatchmakingApp(_cfg(
        durability=DurabilityConfig(journal_dir=str(tmp_path / "h1"),
                                    fsync="window"),
        replication=ReplicationConfig(role="primary", owner="hostB"),
        metrics_port=port),
        replication_hub=hub)
    app2.broker.declare_queue(reply)
    app2.broker.basic_consume(reply, lambda d: None, prefetch=1_000_000)
    await app2.start()
    try:
        assert app2.incidents.by_class.get("failover") == 1
        for i in range(6):
            _publish(app2, f"fq{i}", 2500.0 + (i // 2) * 400.0, reply)

        async def scrape(session, path):
            async with session.get(
                    f"http://127.0.0.1:{port}{path}") as r:
                return r.status, await r.text()

        async with aiohttp.ClientSession() as s:
            results = await asyncio.gather(*(
                scrape(s, p) for p in
                ("/debug/incidents", "/metrics?format=prom") * 4))
            for (inc_status, inc_text), (prom_status, prom_text) in zip(
                    results[0::2], results[1::2]):
                assert inc_status == 200 and prom_status == 200
                body = json.loads(inc_text)
                seqs = [b["seq"] for b in body["incidents"]]
                assert seqs == sorted(seqs)
                assert any(b["class"] == "failover"
                           for b in body["incidents"])
                types, _ = parse_prom(prom_text)
                assert "matchmaking_incidents_captured" in types
                assert "matchmaking_incidents_by_class" in types
                assert "matchmaking_incident_capture_p99_ms" in types
            inc_id = json.loads(results[0][1])["incidents"][0]["id"]
            status, text = await scrape(s, f"/debug/incidents?id={inc_id}")
            assert status == 200
            assert validate_bundle(json.loads(text)) == []
            status, _ = await scrape(s, "/debug/incidents?id=inc-999999")
            assert status == 404
        assert await _quiesce(app2, rt2 := app2.runtime(Q),
                              matched_at_least=6, replication=False)
        spine_rows = app2.spine.window()
        assert [r["seq"] for r in spine_rows] == sorted(
            r["seq"] for r in spine_rows)
        del rt2
    finally:
        await app2.stop()


async def test_capture_during_drain_blocks_nothing_leaks_nothing(tmp_path):
    """A capture fired while windows are in flight must not stall the
    drain or leak a settlement credit: the invariant twin runs
    (debug_invariants), every published pair still matches, and the
    drain predicate settles with the capture counted."""
    app = MatchmakingApp(_cfg(
        forensics=ForensicsConfig(min_interval_s=0.0),
        debug_invariants=True))
    reply = "forensics.drain.replies"
    app.broker.declare_queue(reply)
    app.broker.basic_consume(reply, lambda d: None, prefetch=1_000_000)
    await app.start()
    rt = app.runtime(Q)
    try:
        for i in range(8):
            _publish(app, f"dp{i}", 1000.0 + (i // 2) * 300.0, reply)
        # Fire mid-load, from a worker thread (the spine's cross-thread
        # path): the observer capture runs outside the spine lock.
        t = threading.Thread(target=app.events.append,
                             args=("breaker_trip", Q, "mid-drain fixture"))
        t.start()
        t.join()
        assert app.incidents.by_class.get("breaker_trip") == 1
        assert await _quiesce(app, rt, matched_at_least=8)
        assert app.metrics.counters.get("players_matched") == 8
        assert app.incidents.dropped == 0
    finally:
        await app.stop()


# ---- offline analyzer -------------------------------------------------------


def _synthetic_takeover_bundle() -> dict:
    rows = [
        (1, "replication", "lease_expired", {"epoch": 1}),
        (2, "replication", "epoch_bump", {"epoch": 2, "prev_epoch": 1}),
        (3, "durability", "journal_compacted", {"anchor": 0, "count": 6}),
        (4, "replication", "replay_window", {"epoch": 2, "players": 6}),
        (5, "replication", "failover_takeover", {"epoch": 2, "players": 6}),
        (7, "slo", "slo_burn", {"burn_fast": 100.0, "burn_slow": 100.0}),
        (9, "slo", "slo_burn_clear", {"slo_kind": "latency"}),
    ]
    spine = [{"seq": seq, "mono_ns": seq * 1_000_000, "wall": 100.0 + seq,
              "component": comp, "queue": Q, "kind": kind, "detail": "",
              "refs": refs} for seq, comp, kind, refs in rows]
    return {
        "schema": INCIDENT_SCHEMA, "id": "inc-000042",
        "trigger": {"class": "slo_burn_clear", "seq": 9,
                    "kind": "slo_burn_clear", "queue": Q,
                    "detail": "burn back under threshold", "refs": {},
                    "mono_ns": 9_000_000, "wall": 109.0},
        "captured_wall": 110.0, "capture_ms": 0.8,
        "spine": spine, "spine_digest": "d" * 64,
        "telemetry": {}, "replication": {},
        "journal": {Q: {"seq": 96, "synced_seq": 96, "segment_records": 60,
                        "lsn_range": [36, 96], "tail_digest": "t" * 64}},
        "counters": {},
    }


def test_postmortem_reconstructs_takeover_root_chain_offline():
    """The acceptance chain, from the bundle alone — no live service:
    lease expiry → epoch bump → replay window → takeover → burn →
    burn clear, epoch-matched across components."""
    pm = _load_script("postmortem")
    bundle = _synthetic_takeover_bundle()
    analysis = pm.analyze(bundle)
    assert analysis["problems"] == []
    assert analysis["root_chain_kinds"] == [
        "lease_expired", "epoch_bump", "replay_window",
        "failover_takeover", "slo_burn", "slo_burn_clear"]
    # journal_compacted (seq 3) sits INSIDE the chain's seq span but is
    # not a link — ref resolution, not seq adjacency.
    assert all(ev["kind"] != "journal_compacted"
               for ev in analysis["root_chain"])
    out = io.StringIO()
    pm.render(bundle, out=out)
    text = out.getvalue()
    assert "root chain (6 link(s), cause first)" in text
    assert "journal_dump.py" in text and "--lsn-range 36,96" in text
    # A trigger that rotated out of the spine window still anchors.
    rotated = dict(bundle)
    rotated["spine"] = [r for r in bundle["spine"] if r["seq"] != 9]
    chain = pm.root_chain(rotated)
    assert chain[-1]["kind"] == "slo_burn_clear"
    assert chain[0]["kind"] == "lease_expired"


def test_postmortem_main_exits_2_on_schema_problems(tmp_path, capsys):
    pm = _load_script("postmortem")
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_synthetic_takeover_bundle()))
    assert pm.main([str(good)]) == 0
    assert "root chain" in capsys.readouterr().out
    bad = tmp_path / "bad.json"
    broken = _synthetic_takeover_bundle()
    del broken["spine_digest"]
    bad.write_text(json.dumps(broken))
    assert pm.main([str(bad)]) == 2
    assert "spine_digest" in capsys.readouterr().err


async def test_journal_dump_lsn_range_slices_bundle_window(tmp_path):
    """End to end: run a journaled app, capture a bundle, then slice the
    exact LSN window the bundle's journal watermark names — the records
    come back seq-ordered inside [lo, hi] with admit/terminal types."""
    jdir = str(tmp_path / "journal")
    app = MatchmakingApp(_cfg(
        durability=DurabilityConfig(journal_dir=jdir, fsync="window"),
        forensics=ForensicsConfig(min_interval_s=0.0)))
    reply = "forensics.lsn.replies"
    app.broker.declare_queue(reply)
    app.broker.basic_consume(reply, lambda d: None, prefetch=1_000_000)
    await app.start()
    try:
        for i in range(4):
            _publish(app, f"jp{i}", 1200.0 + (i // 2) * 300.0, reply)
        assert await _quiesce(app, app.runtime(Q), matched_at_least=4)
        bundle = app.incidents.capture(
            "breaker_trip",
            app.events.append("engine_revive", Q, "fixture anchor"))
        lo, hi = bundle["journal"][Q]["lsn_range"]
        assert hi == app.runtime(Q).journal.seq and lo <= hi
    finally:
        await app.stop()
    jd = _load_script("journal_dump")
    sliced = jd.slice_lsn_range(jdir, Q, lo, hi)
    assert "error" not in sliced
    seqs = [r["seq"] for r in sliced["records"]]
    assert seqs and seqs == sorted(seqs)
    assert all(lo <= s <= hi for s in seqs)
    types = {r["type"] for r in sliced["records"]}
    assert "admit" in types and types & {"terminal", "terminals"}
    # CLI shape: --lsn-range requires --queue; a bad range exits early.
    assert jd.main([jdir, "--queue", Q,
                    "--lsn-range", f"{lo},{hi}", "--json"]) == 0
    missing = jd.slice_lsn_range(jdir, "no.such.queue", 0, 10)
    assert "error" in missing
