"""Role-queue party matchmaking (BASELINE config #5) — host-side oracle.

Parties of 1–3 players queue as a unit and must land on the same team; each
team must fill the queue's role slots (e.g. tank/healer/dps/dps/dps) from its
members' declared roles. This turns matching into small constrained
assignment; per SURVEY.md §7 it stays greedy/heuristic and config-gated so it
cannot block the 1v1 north star.

Algorithm (greedy, deterministic):
1. Sort waiting party units by rating (unit rating = mean over members).
2. Slide a window over the sorted units; within each window (spread ≤
   threshold) try to pack units into two teams of exactly ``team_size``
   members each (first-fit decreasing by party size — parties are atomic).
3. A packing is valid iff each team admits a perfect member→role-slot
   assignment (backtracking over ≤ team_size! tiny cases).
4. First valid window wins; quality = 1 − spread/threshold.
"""

from __future__ import annotations

from typing import Sequence

from matchmaking_tpu.config import QueueConfig
from matchmaking_tpu.service.contract import PartyMember, SearchRequest


def unit_rating(req: SearchRequest) -> float:
    total = req.rating + sum(m.rating for m in req.party)
    return total / req.party_size


def _members(req: SearchRequest) -> list[PartyMember]:
    lead = PartyMember(req.id, req.rating, req.rating_deviation, req.roles)
    return [lead, *req.party]


def _roles_cover(team: Sequence[SearchRequest], slots: tuple[str, ...]) -> bool:
    """Perfect assignment members → role slots via backtracking."""
    members = [m for req in team for m in _members(req)]
    if len(members) != len(slots):
        return False
    # Most-constrained-first: fewest eligible members per slot.
    elig = [
        [i for i, m in enumerate(members) if (not m.roles) or slot in m.roles]
        for slot in slots
    ]
    order = sorted(range(len(slots)), key=lambda s: len(elig[s]))
    used = [False] * len(members)

    def assign(k: int) -> bool:
        if k == len(order):
            return True
        for i in elig[order[k]]:
            if not used[i]:
                used[i] = True
                if assign(k + 1):
                    return True
                used[i] = False
        return False

    return assign(0)


def _window_feasible(window: Sequence[SearchRequest],
                     slots: tuple[str, ...]) -> bool:
    """Cheap necessary condition before the expensive pack: for every role,
    the window must hold at least 2×(its slot count) eligible members
    (wildcard-role members are eligible for everything). Filters the common
    production shape — a dps-heavy pool where most windows lack the 2 tanks
    / 2 healers — for ~5 µs instead of a failed pack + O(k²) swap-repair
    (~0.3-1 ms each; this check removed ~35 ms/arrival in the ladder
    bench)."""
    if not slots:
        return True
    members = [m for u in window for m in _members(u)]
    for role in set(slots):
        needed = 2 * slots.count(role)
        elig = 0
        for m in members:
            if (not m.roles) or role in m.roles:
                elig += 1
                if elig >= needed:
                    break
        if elig < needed:
            return False
    return True


def _pack_two_teams(units: Sequence[SearchRequest], team_size: int,
                    slots: tuple[str, ...]):
    """First-fit-decreasing pack of atomic party units into two exact teams
    with role coverage. Returns (team_a, team_b) or None."""
    order = sorted(units, key=lambda u: (-u.party_size, unit_rating(u)))
    team_a: list[SearchRequest] = []
    team_b: list[SearchRequest] = []
    size_a = size_b = 0
    for u in order:
        if size_a + u.party_size <= team_size:
            team_a.append(u)
            size_a += u.party_size
        elif size_b + u.party_size <= team_size:
            team_b.append(u)
            size_b += u.party_size
    if size_a != team_size or size_b != team_size:
        return None
    if slots and not (_roles_cover(team_a, slots) and _roles_cover(team_b, slots)):
        # One swap-repair pass: try exchanging equal-size units across teams.
        for i, ua in enumerate(team_a):
            for j, ub in enumerate(team_b):
                if ua.party_size != ub.party_size:
                    continue
                team_a[i], team_b[j] = ub, ua
                if _roles_cover(team_a, slots) and _roles_cover(team_b, slots):
                    return tuple(team_a), tuple(team_b)
                team_a[i], team_b[j] = ua, ub
        return None
    return tuple(team_a), tuple(team_b)


def try_party_match(units: Sequence[SearchRequest], queue: QueueConfig,
                    now: float, engine,
                    focus: SearchRequest | None = None,
                    ) -> tuple[tuple[tuple[SearchRequest, ...], ...], float] | None:
    """Try to form one match from waiting party units. Returns (teams,
    quality) or None. ``engine`` provides ``effective_threshold``.

    ``focus``: arrival-triggered fast path — only windows CONTAINING this
    unit are tried. Exact under the greedy invariant (every earlier arrival
    exhaustively tried its windows, and removals never create matches), so
    any match among old units alone would already have formed; callers must
    pass ``focus=None`` when the invariant is broken: after restore() (a
    checkpoint can hold latent matches) or with threshold widening enabled
    (old windows can become valid by waiting). Reduces per-arrival cost
    from O(n) packs to O(need + slack) packs."""
    need = 2 * queue.team_size
    total = sum(u.party_size for u in units)
    if total < need:
        return None
    su = sorted(units, key=unit_rating)
    n = len(su)
    # Window-slack bound: for each lo, only windows with at most
    # WINDOW_SLACK units beyond the minimal member count are tried. An
    # unpackable minimal window almost never becomes packable by adding
    # many more units (packing fails on role composition, and first-fit
    # considers only units that still fit the two teams), while each extra
    # extension costs a full pack + role backtracking. Unbounded, this
    # loop is O(n^2) packs — measured at seconds per REQUEST by ~200
    # waiting units; bounded it is O(n * slack) and the greedy semantics
    # (tightest-first: windows grow from minimal, first valid wins) are
    # unchanged.
    WINDOW_SLACK = 6
    if focus is not None:
        fidx = next((i for i, u in enumerate(su) if u.id == focus.id), None)
        if fidx is None:
            return None
        # Windows must include fidx: lo ≤ fidx, and minimal windows have at
        # most ``need`` units (every unit carries ≥1 member), slack-extended
        # ones at most need + WINDOW_SLACK.
        lo_iter = range(max(0, fidx - (need + WINDOW_SLACK) + 1), fidx + 1)
    else:
        fidx = -1
        lo_iter = range(n)
    for lo in lo_iter:
        members = 0
        extra = 0
        for hi in range(lo, n):
            members += su[hi].party_size
            if members < need:
                continue
            # extra counts EVERY completed window — including hi < fidx ones
            # in focus mode — so the focused scan tries exactly the subset
            # of full-scan windows that contain the focus unit; counting
            # only from fidx would let focus mode reach windows the full
            # scan abandons at the slack bound, and the two modes would
            # form different matches on identical pools.
            extra += 1
            if extra > WINDOW_SLACK:
                break
            if hi < fidx:
                # Already tried by an earlier arrival (greedy invariant).
                continue
            window = su[lo:hi + 1]
            spread = unit_rating(window[-1]) - unit_rating(window[0])
            # Window must fit every member unit's effective threshold
            # (honors per-request overrides + widening).
            thr = min(engine.effective_threshold(u, now) for u in window)
            if spread > thr:
                break
            if not _window_feasible(window, queue.role_slots):
                continue
            packed = _pack_two_teams(window, queue.team_size, queue.role_slots)
            if packed is not None:
                qual = max(0.0, 1.0 - spread / thr) if thr > 0 else 0.0
                return packed, qual
    return None
