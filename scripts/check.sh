#!/usr/bin/env bash
# The repo gate, in order:
#   1. matchlint (python -m matchmaking_tpu.analysis) — fails on any
#      finding outside analysis/baseline.json. Runs FIRST because it is
#      seconds, not minutes, and a lock-discipline bug should fail fast.
#   2. tier-1 tests (the ROADMAP.md verify recipe's pytest selection).
# Lint time is excluded from any bench numbers by construction: bench.py
# never invokes this script (see BENCH_CONFIGS.md).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== matchlint =="
JAX_PLATFORMS=cpu python -m matchmaking_tpu.analysis

echo "== attribution smoke =="
# ISSUE 6 fast gate: a seeded 400-player soak must decompose every settled
# trace into work + wait that sums to its e2e span (telescoping identity),
# with the histogram-side p99 agreeing within one log bucket.
JAX_PLATFORMS=cpu python -m pytest tests/test_attribution.py -q \
    -k 'smoke' --continue-on-collection-errors -p no:cacheprovider

echo "== overload =="
# The overload-control suite (ISSUE 5) runs by marker first: admission /
# shed / deadline / drain regressions fail fast and by name before the
# full tier-1 sweep repeats them in context.
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'overload and not slow' \
    --continue-on-collection-errors -p no:cacheprovider

echo "== qos =="
# Tiered-QoS suite (ISSUE 7): priority partitions / EDF ordering /
# pool-resident deadline expiry regressions fail fast and by name.
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'qos and not slow' \
    --continue-on-collection-errors -p no:cacheprovider

echo "== tier-1 =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
