"""Request batcher: collects a time/size window per queue, dispatches once.

This is the structural pivot of the rebuild (BASELINE north_star: "the AMQP
consumer batches a window of incoming search requests and hands them to a
JAX sidecar"): instead of one engine call per delivery, deliveries accumulate
until ``max_batch`` or ``max_wait_ms``, whichever first, then flush as one
window. Windows per queue are serialized — the next window is not dispatched
until the previous one's flush callback returns — which is the atomicity
guarantee (a matched player is out of the pool before anyone else can see
them; SURVEY.md §7 "Hard parts").

Concurrency contract: all state (``_pending``/``_submitted``/the events)
is event-loop-confined — ``submit()`` must be called from the loop, never
from a worker thread (use ``loop.call_soon_threadsafe`` to cross). There
is deliberately no lock here for matchlint's guarded-by rule to check:
the ``_run`` task and submitters interleave only at awaits, and ``_cut``
is await-free, so a window slice is atomic by construction.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, Generic, TypeVar

from matchmaking_tpu.config import BatcherConfig

T = TypeVar("T")


class Batcher(Generic[T]):
    def __init__(self, cfg: BatcherConfig,
                 flush: Callable[[list[T]], Awaitable[None]],
                 observe_window: Callable[[int, float], None] | None = None,
                 sort_key: "Callable[[T], object] | None" = None):
        self.cfg = cfg
        self._flush = flush
        #: Earliest-deadline-first window cutting (OverloadConfig.edf): when
        #: set, each cut re-orders the pending backlog by this key — the
        #: runtime keys on (tier, absolute x-deadline) — so a full window
        #: is exactly the best ``max_batch`` candidates, never an
        #: arrival-order prefix that strands a near-deadline tier-0
        #: request behind backlog. The sort is stable (FIFO within equal
        #: keys) and the key must be a pure function of the item (no clock
        #: reads — matchlint's determinism rule owns that), so cut
        #: composition replays bit-identically. PUBLIC like the
        #: max_batch/max_wait_ms live knobs below: the online autotuner's
        #: EDF toggle (_QueueRuntime.set_edf) swaps it at tick time, and
        #: the next _cut picks the change up.
        self.sort_key = sort_key
        #: Observability hook, called once per cut window with
        #: ``(window_size, open_age_seconds)`` — batch fill and batcher
        #: wait are BASELINE headline metrics (utils/metrics docstring) and
        #: the first suspect in any p99 investigation, so the batcher
        #: reports them itself instead of making callers reverse-engineer
        #: the window boundaries from item timestamps.
        self._observe = observe_window
        #: Live window knobs, initialized from the (frozen) config. The
        #: online autotuner (control/autotune.py, ISSUE 13) adjusts
        #: ``max_wait_ms`` within its declared safe range at tick time;
        #: ``_run`` re-reads it every window so a change takes effect on
        #: the NEXT cut, never mid-window. Event-loop-confined like the
        #: rest of the batcher state.
        self.max_batch = cfg.max_batch
        self.max_wait_ms = cfg.max_wait_ms
        self._pending: list[T] = []
        #: Per-item submit times, parallel to _pending — the cut reports
        #: the OLDEST remaining item's true wait, so carried-over backlog
        #: (items sliced into a later window under saturation) is not
        #: under-reported exactly when queueing is the p99 cause.
        self._submitted: list[float] = []
        self._first = asyncio.Event()   # first item of a window arrived
        self._full = asyncio.Event()    # size trigger
        self._closed = False
        self._task = asyncio.create_task(self._run())

    def submit(self, item: T) -> None:
        if self._closed:
            raise RuntimeError("batcher closed")
        self._pending.append(item)
        if self._observe is not None:
            self._submitted.append(time.monotonic())
        self._first.set()
        if len(self._pending) >= self.max_batch:
            self._full.set()

    def submit_many(self, items: "list[T]") -> None:
        """Burst submit (ISSUE 12, the consume_batch ingress): one extend,
        one clock read, and one trigger check for a whole consume burst —
        the per-item bookkeeping of N ``submit`` calls, amortized. The
        shared submit timestamp is the burst's arrival instant, which is
        when every member actually became pending."""
        if self._closed:
            raise RuntimeError("batcher closed")
        if not items:
            return
        self._pending.extend(items)
        if self._observe is not None:
            now = time.monotonic()
            self._submitted.extend([now] * len(items))
        self._first.set()
        if len(self._pending) >= self.max_batch:
            self._full.set()

    def _cut(self) -> list[T]:
        """Slice the next window off the pending list and report it."""
        if self.sort_key is not None and len(self._pending) > 1:
            # EDF: stable-sort the WHOLE backlog, then slice — the window
            # is the min-key prefix, and the carried-over remainder stays
            # ordered for the next cut. O(n log n) on the backlog; the
            # backlog is bounded by admission (and by prefetch without it).
            key = self.sort_key
            order = sorted(range(len(self._pending)),
                           key=lambda i: key(self._pending[i]))
            self._pending = [self._pending[i] for i in order]
            if self._observe is not None:
                self._submitted = [self._submitted[i] for i in order]
        window = self._pending[: self.max_batch]
        self._pending = self._pending[self.max_batch:]
        if self._observe is not None and window:
            # Oldest item still PENDING at the cut (window + remainder):
            # under FIFO that is index 0, the pre-EDF behavior exactly;
            # under EDF a starved low-tier item rides the remainder across
            # many cuts, and restricting the age to the window would
            # under-report batcher wait precisely while EDF is starving
            # someone — the signal the adaptive limiter feeds on.
            age = time.monotonic() - min(self._submitted)
            self._submitted = self._submitted[len(window):]
            self._observe(len(window), max(0.0, age))
        return window

    async def _run(self) -> None:
        while not self._closed:
            # Re-read per window: the autotuner may retune the wait knob.
            max_wait = self.max_wait_ms / 1000.0
            if not self._pending:
                # Idle: wake immediately on the window's first item.
                self._first.clear()
                try:
                    await asyncio.wait_for(self._first.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    continue
                if not self._pending:
                    continue
            # Window open: close after max_wait unless the size trigger
            # fires first.
            self._full.clear()
            if len(self._pending) < self.max_batch:
                try:
                    await asyncio.wait_for(self._full.wait(), timeout=max_wait)
                except asyncio.TimeoutError:
                    pass
            if not self._pending:
                continue
            window = self._cut()
            try:
                await self._flush(window)
            except Exception:
                # The flush owner handles its own errors; a crash here must
                # not kill the batching loop (supervision logs it).
                import logging

                logging.getLogger(__name__).exception("batch flush crashed")

    def flush_hint(self) -> None:
        """Close the current window early (e.g. at shutdown)."""
        self._full.set()

    @property
    def depth(self) -> int:
        return len(self._pending)

    async def close(self) -> None:
        """Graceful close: let any in-flight flush finish (cancelling it
        would drop a window already sliced out of ``_pending``), then flush
        the remainder."""
        self._closed = True
        self._first.set()
        self._full.set()
        try:
            await asyncio.wait_for(self._task, timeout=5.0)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        while self._pending:
            await self._flush(self._cut())
