// Batch wire-request decoder: raw JSON bodies -> columnar arrays.
//
// The rebuild's native runtime component (SURVEY.md §2: the reference's
// native layer is the BEAM VM + Erlang AMQP stack; here the hot host-side
// loop is the wire codec, so it is C++). One call decodes a whole window of
// AMQP message bodies into the engine's RequestColumns layout; rows the fast
// path cannot express (parties, roles, escaped strings) are flagged
// NEEDS_PYTHON and re-decoded by the Python contract module (exact same
// validation rules — contract.decode_request is the semantic source of
// truth, and tests hold the two decoders to identical outputs).
//
// Build: g++ -O2 -shared -fPIC -o libmmcodec.so codec.cc   (no deps)
// Binding: ctypes (matchmaking_tpu/native/codec.py).

#include <cctype>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cmath>

namespace {

enum Status : int32_t {
  OK = 0,
  NEEDS_PYTHON = 1,   // party/roles present, escapes, or anything exotic
  BAD_JSON = 2,
  MISSING_FIELD = 3,
  BAD_TYPE = 4,
  BAD_RATING = 5,
  BAD_THRESHOLD = 6,
};

struct Cursor {
  const char* p;
  const char* end;
  bool ok = true;

  bool done() const { return p >= end; }
  char peek() const { return p < end ? *p : '\0'; }
  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }
};

// Skip any JSON value (used for keys we ignore). Depth-counted, no
// allocation. Returns false on malformed input.
bool skip_value(Cursor& c);

bool skip_string(Cursor& c) {
  // Assumes *c.p == '"'.
  ++c.p;
  while (c.p < c.end) {
    char ch = *c.p++;
    if (ch == '\\') {
      if (c.p < c.end) ++c.p;  // skip escaped char (incl. start of \uXXXX)
      continue;
    }
    if (ch == '"') return true;
  }
  return false;
}

bool skip_number(Cursor& c) {
  const char* start = c.p;
  while (c.p < c.end && (isdigit((unsigned char)*c.p) || *c.p == '-' ||
                         *c.p == '+' || *c.p == '.' || *c.p == 'e' ||
                         *c.p == 'E'))
    ++c.p;
  return c.p > start;
}

bool skip_literal(Cursor& c, const char* lit, size_t len) {
  if ((size_t)(c.end - c.p) < len || strncmp(c.p, lit, len) != 0) return false;
  c.p += len;
  return true;
}

bool skip_container(Cursor& c, char open, char close) {
  // Assumes *c.p == open.
  int depth = 0;
  while (c.p < c.end) {
    char ch = *c.p;
    if (ch == '"') {
      if (!skip_string(c)) return false;
      continue;
    }
    ++c.p;
    if (ch == open) ++depth;
    else if (ch == close) {
      if (--depth == 0) return true;
    }
  }
  return false;
}

bool skip_value(Cursor& c) {
  c.skip_ws();
  char ch = c.peek();
  if (ch == '"') return skip_string(c);
  if (ch == '{') return skip_container(c, '{', '}');
  if (ch == '[') return skip_container(c, '[', ']');
  if (ch == 't') return skip_literal(c, "true", 4);
  if (ch == 'f') return skip_literal(c, "false", 5);
  if (ch == 'n') return skip_literal(c, "null", 4);
  return skip_number(c);
}

// Parse a string value without escapes into [out, out+cap). Returns length,
// -1 on escape/overflow (-> NEEDS_PYTHON), -2 on malformed.
int parse_plain_string(Cursor& c, char* out, int cap) {
  if (c.peek() != '"') return -2;
  ++c.p;
  int n = 0;
  while (c.p < c.end) {
    char ch = *c.p++;
    if (ch == '"') return n;
    if (ch == '\\') return -1;
    if (n >= cap) return -1;
    out[n++] = ch;
  }
  return -2;
}

struct Number {
  double value;
  bool is_number;
};

Number parse_number(Cursor& c) {
  char buf[64];
  const char* start = c.p;
  if (!skip_number(c) || c.p - start >= (long)sizeof(buf)) return {0.0, false};
  size_t len = c.p - start;
  memcpy(buf, start, len);
  buf[len] = '\0';
  char* endp = nullptr;
  double v = strtod(buf, &endp);
  return {v, endp == buf + len};
}

constexpr int kMaxStr = 256;  // per-field cap for id/region/mode strings

struct Row {
  char id[kMaxStr]; int id_len = -1;
  char region[kMaxStr]; int region_len = -1;
  char mode[kMaxStr]; int mode_len = -1;
  double rating = 0.0; bool has_rating = false;
  double rd = 350.0;
  double threshold = NAN;
  int32_t status = OK;
};

bool key_is(const char* key, int len, const char* name) {
  return (int)strlen(name) == len && memcmp(key, name, len) == 0;
}

void decode_one(const char* buf, int len, Row& row) {
  Cursor c{buf, buf + len};
  c.skip_ws();
  if (c.peek() != '{') { row.status = BAD_JSON; return; }
  ++c.p;
  bool first = true;
  while (true) {
    c.skip_ws();
    if (c.peek() == '}') { ++c.p; break; }
    if (!first) {
      if (c.peek() != ',') { row.status = BAD_JSON; return; }
      // (comma consumed below after detecting it's not the first pair)
    }
    if (c.peek() == ',') ++c.p;
    first = false;
    c.skip_ws();
    char key[64];
    int klen = parse_plain_string(c, key, sizeof(key));
    if (klen == -1) { row.status = NEEDS_PYTHON; return; }
    if (klen < 0) { row.status = BAD_JSON; return; }
    c.skip_ws();
    if (c.peek() != ':') { row.status = BAD_JSON; return; }
    ++c.p;
    c.skip_ws();

    if (key_is(key, klen, "id")) {
      row.id_len = parse_plain_string(c, row.id, kMaxStr);
      if (row.id_len == -1) { row.status = NEEDS_PYTHON; return; }
      if (row.id_len < 0) {
        // Non-string id: bools/numbers are a type error per contract.
        if (!skip_value(c)) { row.status = BAD_JSON; return; }
        row.status = BAD_TYPE; return;
      }
    } else if (key_is(key, klen, "region")) {
      row.region_len = parse_plain_string(c, row.region, kMaxStr);
      if (row.region_len == -1) { row.status = NEEDS_PYTHON; return; }
      if (row.region_len < 0) {
        // contract: str(payload.get(...)) — non-strings coerce; punt.
        row.status = NEEDS_PYTHON;
        if (!skip_value(c)) row.status = BAD_JSON;
        return;
      }
    } else if (key_is(key, klen, "game_mode")) {
      row.mode_len = parse_plain_string(c, row.mode, kMaxStr);
      if (row.mode_len == -1) { row.status = NEEDS_PYTHON; return; }
      if (row.mode_len < 0) {
        row.status = NEEDS_PYTHON;
        if (!skip_value(c)) row.status = BAD_JSON;
        return;
      }
    } else if (key_is(key, klen, "rating")) {
      if (c.peek() == 't' || c.peek() == 'f') { row.status = BAD_TYPE; return; }
      Number num = parse_number(c);
      if (!num.is_number) { row.status = BAD_TYPE; return; }
      row.rating = num.value; row.has_rating = true;
    } else if (key_is(key, klen, "rating_deviation")) {
      if (c.peek() == 't' || c.peek() == 'f') { row.status = BAD_TYPE; return; }
      Number num = parse_number(c);
      if (!num.is_number) { row.status = BAD_TYPE; return; }
      row.rd = num.value;
    } else if (key_is(key, klen, "rating_threshold")) {
      if (c.peek() == 't' || c.peek() == 'f') { row.status = BAD_TYPE; return; }
      Number num = parse_number(c);
      if (!num.is_number) { row.status = BAD_TYPE; return; }
      row.threshold = num.value;
    } else if (key_is(key, klen, "roles") || key_is(key, klen, "party")) {
      // Non-empty arrays need the full Python decoder; [] is a no-op.
      c.skip_ws();
      if (c.peek() == '[') {
        const char* probe = c.p + 1;
        while (probe < c.end && (*probe == ' ' || *probe == '\n' ||
                                 *probe == '\t' || *probe == '\r'))
          ++probe;
        if (probe < c.end && *probe == ']') {
          c.p = probe + 1;
        } else {
          row.status = NEEDS_PYTHON;
          return;
        }
      } else {
        row.status = BAD_TYPE; return;
      }
    } else {
      if (!skip_value(c)) { row.status = BAD_JSON; return; }
    }
  }
  c.skip_ws();
  if (!c.done()) { row.status = BAD_JSON; return; }

  // Validation, mirroring contract.decode_request.
  if (row.id_len < 0 || !row.has_rating) { row.status = MISSING_FIELD; return; }
  if (!(row.rating > -1e5 && row.rating < 1e5)) { row.status = BAD_RATING; return; }
  if (row.rd < 0) { row.status = BAD_RATING; return; }
  if (!std::isnan(row.threshold) && row.threshold <= 0) {
    row.status = BAD_THRESHOLD; return;
  }
}

}  // namespace

extern "C" {

// Decode n message bodies. Outputs (caller-allocated):
//   rating[n] f32, rd[n] f32, threshold[n] f32 (NaN = absent),
//   status[n] i32, arena char buffer (cap bytes) holding id/region/mode
//   bytes back-to-back, offsets id_off/region_off/mode_off each [n+1]
//   (empty string = region/mode absent -> wildcard).
// Returns bytes used in arena, or -1 if the arena overflowed (caller
// retries with a bigger arena).
int64_t mm_decode_requests(const char** bufs, const int32_t* lens, int32_t n,
                           float* rating, float* rd, float* threshold,
                           int32_t* status, char* arena, int64_t cap,
                           int64_t* id_off, int64_t* region_off,
                           int64_t* mode_off) {
  int64_t used = 0;
  for (int32_t i = 0; i < n; ++i) {
    Row row;
    decode_one(bufs[i], lens[i], row);
    status[i] = row.status;
    rating[i] = (float)row.rating;
    rd[i] = (float)row.rd;
    threshold[i] = (float)row.threshold;
    id_off[i] = used;
    if (row.status == OK) {
      if (used + row.id_len > cap) return -1;
      memcpy(arena + used, row.id, row.id_len);
      used += row.id_len;
    }
    region_off[i] = used;
    if (row.status == OK && row.region_len > 0) {
      if (used + row.region_len > cap) return -1;
      memcpy(arena + used, row.region, row.region_len);
      used += row.region_len;
    }
    mode_off[i] = used;
    if (row.status == OK && row.mode_len > 0) {
      if (used + row.mode_len > cap) return -1;
      memcpy(arena + used, row.mode, row.mode_len);
      used += row.mode_len;
    }
    // Sentinel end for row i is the next row's id_off (or final `used`).
  }
  id_off[n] = used;
  region_off[n] = used;  // unused; kept for symmetric shape
  mode_off[n] = used;
  return used;
}

}  // extern "C"
