"""Wire-contract tests: encode/decode round-trips + validation rejects
(SURVEY.md §4: property-test contract encode/decode round-trips)."""

import json

import pytest

from matchmaking_tpu.service import contract
from matchmaking_tpu.service.contract import (
    ANY,
    ContractError,
    MatchResult,
    PartyMember,
    SearchRequest,
    SearchResponse,
    decode_request,
    decode_response,
    encode_request,
    encode_response,
)


def test_minimal_request_roundtrip():
    req = SearchRequest(id="p1", rating=1500.0)
    got = decode_request(encode_request(req))
    assert got.id == "p1"
    assert got.rating == 1500.0
    assert got.region == ANY and got.game_mode == ANY
    assert got.rating_threshold is None
    assert got.party_size == 1


def test_full_request_roundtrip():
    req = SearchRequest(
        id="lead", rating=1800.5, rating_deviation=120.0, game_mode="ranked",
        region="eu", rating_threshold=42.0, roles=("tank", "dps"),
        party=(PartyMember("m2", 1750.0, 90.0, ("healer",)),
               PartyMember("m3", 1820.0)),
    )
    got = decode_request(encode_request(req))
    assert got.rating_deviation == 120.0
    assert got.game_mode == "ranked" and got.region == "eu"
    assert got.rating_threshold == 42.0
    assert got.roles == ("tank", "dps")
    assert got.party_size == 3
    assert got.party[0].roles == ("healer",)
    assert got.all_ids() == ("lead", "m2", "m3")


def test_request_transport_metadata_not_in_body():
    req = SearchRequest(id="p", rating=1.0, reply_to="q.reply", correlation_id="c1")
    body = json.loads(encode_request(req))
    assert "reply_to" not in body and "correlation_id" not in body


@pytest.mark.parametrize("body,code", [
    (b"not json", "bad_json"),
    (b"[1,2]", "bad_json"),
    (b"{}", "missing_field"),
    (b'{"id": "p"}', "missing_field"),
    (b'{"id": 7, "rating": 1}', "bad_type"),
    (b'{"id": "p", "rating": "high"}', "bad_type"),
    (b'{"id": "p", "rating": true}', "bad_type"),
    (b'{"id": "p", "rating": 1e9}', "bad_rating"),
    (b'{"id": "p", "rating": 1, "rating_deviation": -1}', "bad_rating"),
    (b'{"id": "p", "rating": 1, "rating_threshold": 0}', "bad_threshold"),
    (b'{"id": "p", "rating": 1, "party": "x"}', "bad_type"),
    (b'{"id": "p", "rating": 1, "party": [{"id":"p","rating":1}]}', "duplicate_player"),
], ids=lambda v: v if isinstance(v, str) else "body")
def test_decode_rejects(body, code):
    with pytest.raises(ContractError) as ei:
        decode_request(body)
    assert ei.value.code == code


def test_party_too_large():
    party = [{"id": f"m{i}", "rating": 1} for i in range(5)]
    body = json.dumps({"id": "p", "rating": 1, "party": party}).encode()
    with pytest.raises(ContractError) as ei:
        decode_request(body)
    assert ei.value.code == "party_too_large"


def test_response_roundtrip_matched():
    resp = SearchResponse(
        status="matched", player_id="p1",
        match=MatchResult("m-1", ("p1", "p2"), (("p1",), ("p2",)), 0.875),
        latency_ms=12.5,
    )
    got = decode_response(encode_response(resp))
    assert got.status == "matched"
    assert got.match.players == ("p1", "p2")
    assert got.match.teams == (("p1",), ("p2",))
    assert got.match.quality == 0.875
    assert got.latency_ms == 12.5


def test_response_roundtrip_error():
    resp = SearchResponse(status="error", player_id="p", error_code="bad_json",
                          error_reason="nope")
    got = decode_response(encode_response(resp))
    assert got.status == "error" and got.error_code == "bad_json"
    assert got.match is None


def test_fuzz_roundtrip(rng):
    for _ in range(200):
        req = SearchRequest(
            id=f"p{rng.integers(1e9)}",
            rating=float(rng.uniform(-5000, 5000)),
            rating_deviation=float(rng.uniform(0, 500)),
            game_mode=rng.choice(["*", "ranked", "casual"]),
            region=rng.choice(["*", "eu", "na", "apac"]),
            rating_threshold=float(rng.uniform(1, 500)) if rng.random() < 0.5 else None,
        )
        got = decode_request(encode_request(req))
        assert got.id == req.id
        assert got.rating == pytest.approx(req.rating)
        assert got.region == req.region and got.game_mode == req.game_mode
        assert (got.rating_threshold is None) == (req.rating_threshold is None)


def test_roles_validation():
    with pytest.raises(ContractError) as ei:
        decode_request(b'{"id":"p","rating":1,"roles":"tank"}')
    assert ei.value.code == "bad_type"
    with pytest.raises(ContractError):
        decode_request(b'{"id":"p","rating":1,"roles":5}')
    with pytest.raises(ContractError):
        decode_request(b'{"id":"p","rating":1,"roles":[1,2]}')
    got = decode_request(b'{"id":"p","rating":1,"roles":["tank","dps"]}')
    assert got.roles == ("tank", "dps")


def test_config_from_env_top_level_scalars(monkeypatch):
    from matchmaking_tpu.config import Config
    monkeypatch.setenv("MM_WORKERS", "4")
    monkeypatch.setenv("MM_SEED", "7")
    cfg = Config.from_env()
    assert cfg.workers == 4 and cfg.seed == 7


def test_numeric_fields_reject_non_numbers():
    for body in (b'{"id":"a","rating":1500,"rating_deviation":"high"}',
                 b'{"id":"a","rating":1500,"rating_threshold":"low"}',
                 b'{"id":"a","rating":1500,"rating_deviation":true}',
                 b'{"id":"a","rating":1,"party":[{"id":"m","rating":1,"rating_deviation":"x"}]}'):
        with pytest.raises(ContractError) as ei:
            decode_request(body)
        assert ei.value.code in ("bad_type", "bad_rating")
