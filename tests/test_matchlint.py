"""matchlint (matchmaking_tpu/analysis): seeded regression tests.

Every rule gets at least one fixture-triggered POSITIVE (the acceptance
bar: a rule that can't fire is decoration), the PR 2 await-window
double-match pattern is proven statically caught, and the `lint`-marked
node runs the full analyzer over the repo — matchlint wired into tier-1.
"""

import pytest

from matchmaking_tpu.analysis.engine import analyze_repo, analyze_source


def _rules(findings):
    return [f.rule for f in findings]


# ---- await-under-lock ------------------------------------------------------

def test_await_under_lock_fires_on_non_sanctioned_await():
    findings = analyze_source('''
import asyncio

class Runtime:
    def __init__(self):
        self._engine_lock = asyncio.Lock()

    async def flush(self, ctx):
        async with self._engine_lock:
            await self.pipeline.run(ctx)
''', path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["await-under-lock"]
    assert findings[0].line == 10
    assert "pipeline.run" in findings[0].message


def test_await_under_lock_sanctions_to_thread_and_drain():
    findings = analyze_source('''
import asyncio

class Runtime:
    def __init__(self):
        self._engine_lock = asyncio.Lock()

    async def flush(self, window, now):
        async with self._engine_lock:
            await self._drain_engine(now)
            out = await asyncio.to_thread(self.engine.search, window, now)
        return out
''', path="matchmaking_tpu/service/fixture.py")
    assert findings == []


def test_pr2_await_window_double_match_pattern_is_caught():
    """Re-introducing PR 2's race — pool-state mutation across an await
    inside ``_engine_lock`` (the dup delivery that passed the dedup check
    re-admitting while its twin's window was in flight) — is caught
    STATICALLY, without running chaos."""
    findings = analyze_source('''
import asyncio

class Runtime:
    def __init__(self):
        self._engine_lock = asyncio.Lock()
        # guarded-by: _engine_lock
        self._recent = {}

    async def dispatch(self, pairs, now):
        async with self._engine_lock:
            stale = {p for p, d in pairs if p in self._recent}
            await self.broker.confirm(stale)
            for p, _d in pairs:
                self._recent[p] = now
''', path="matchmaking_tpu/service/fixture.py")
    assert "await-under-lock" in _rules(findings)
    bad = next(f for f in findings if f.rule == "await-under-lock")
    assert "broker.confirm" in bad.message


# ---- guarded-by ------------------------------------------------------------

GUARDED_CLASS = '''
import asyncio

class Runtime:
    def __init__(self):
        self._engine_lock = asyncio.Lock()
        # guarded-by: _engine_lock
        self._inflight_meta = {}

    # holds-lock: _engine_lock
    def _finish(self, tok):
        self._inflight_meta.pop(tok, None)

    def _collect_ready_locked(self, now):
        self._inflight_meta.clear()

    async def good(self, tok, meta):
        async with self._engine_lock:
            self._inflight_meta[tok] = meta
            self._finish(tok)
%s
'''


def test_guarded_by_accepts_disciplined_mutations():
    findings = analyze_source(GUARDED_CLASS % "",
                              path="matchmaking_tpu/service/fixture.py")
    assert findings == []


def test_guarded_by_collects_annotated_assignment_declarations():
    """Regression: `self.x: T = ...` (ast.AnnAssign) must register a
    guarded-by declaration exactly like a plain assignment — app.py's
    `_inflight_meta` declaration is annotated."""
    findings = analyze_source("""
import asyncio

class Runtime:
    def __init__(self):
        self._engine_lock = asyncio.Lock()
        # guarded-by: _engine_lock
        self._inflight_meta: dict[int, str] = {}

    def sweep(self, tok):
        self._inflight_meta.pop(tok, None)
""", path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["guarded-by"]


def test_guarded_by_flags_unlocked_mutation():
    findings = analyze_source(GUARDED_CLASS % '''
    def sweep(self, tok):
        self._inflight_meta.pop(tok, None)
''', path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["guarded-by"]
    assert "_inflight_meta" in findings[0].message
    assert findings[0].context == "Runtime.sweep"


def test_guarded_by_flags_unlocked_call_to_holding_method():
    findings = analyze_source(GUARDED_CLASS % '''
    async def tick(self, tok):
        self._finish(tok)
''', path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["guarded-by"]
    assert "_finish" in findings[0].message


def test_guarded_by_flags_attribute_store_through_guarded_object():
    findings = analyze_source('''
import asyncio

class Runtime:
    def __init__(self):
        self._engine_lock = asyncio.Lock()
        # guarded-by: _engine_lock
        self.engine = None

    async def poke(self):
        self.engine.device_error = None
''', path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["guarded-by"]


# ---- blocking-call ---------------------------------------------------------

def test_blocking_call_fires_in_async_bodies_only():
    findings = analyze_source('''
import time

async def handler(arr):
    time.sleep(0.1)
    f = open("/tmp/x")
    arr.block_until_ready()
    n = arr.item()

def sync_helper():
    time.sleep(0.1)  # worker-thread code: fine

async def off_loop():
    def run():
        time.sleep(0.1)  # nested sync def: runs via to_thread
    return run
''', path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["blocking-call"] * 4
    assert all(f.context == "handler" for f in findings)


# ---- determinism -----------------------------------------------------------

def test_determinism_flags_unseeded_rng_and_wallclock_deadlines():
    findings = analyze_source('''
import random
import time
import numpy as np

def faults():
    rng = random.Random()
    g = np.random.default_rng()
    x = random.random()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        pass
''', path="matchmaking_tpu/utils/fixture.py")
    assert _rules(findings) == ["determinism"] * 5
    seeded = analyze_source('''
import random
import time

def fine():
    rng = random.Random(42)
    deadline = time.monotonic() + 5.0
    return rng.random(), deadline
''', path="matchmaking_tpu/utils/fixture.py")
    assert seeded == []


# ---- ignore comments -------------------------------------------------------

def test_inline_ignore_with_reason_suppresses_and_bare_does_not():
    body = '''
import time

async def handler():
    # matchlint: ignore[blocking-call] admin endpoint, bounded one-shot
    time.sleep(0.1)
'''
    assert analyze_source(body,
                          path="matchmaking_tpu/service/fixture.py") == []
    bare = body.replace(" admin endpoint, bounded one-shot", "")
    findings = analyze_source(bare,
                              path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["blocking-call"]


# ---- recompile -------------------------------------------------------------

def test_recompile_static_flags_loop_variable_capture():
    findings = analyze_source('''
import jax

def build_steps():
    fns = []
    for k in range(3):
        fns.append(jax.jit(lambda x: x * k))
    return fns
''', path="matchmaking_tpu/engine/kernels.py")
    assert _rules(findings) == ["recompile"]
    assert "'k'" in findings[0].message and "for-loop" in findings[0].message


def test_recompile_static_accepts_factory_constants():
    findings = analyze_source('''
import functools

import jax

def kernel_factory(capacity, top_k):
    @functools.partial(jax.jit, donate_argnums=0)
    def step(pool, packed):
        return pool, packed[:top_k] * capacity

    return step
''', path="matchmaking_tpu/engine/kernels.py")
    assert findings == []


def test_recompile_dynamic_catches_jaxpr_drift():
    import jax.numpy as jnp

    from matchmaking_tpu.analysis import recompile

    calls = {"n": 0}

    def drifting(x):
        calls["n"] += 1
        return x + calls["n"]

    out = []
    recompile._drift(drifting, lambda v: (jnp.zeros(4),), "drifting",
                     "fixture", out)
    assert len(out) == 1 and "jaxpr drift" in out[0].message

    def stable(x):
        return x * 2.0

    out = []
    recompile._drift(stable, lambda v: (jnp.full(4, float(v)),), "stable",
                     "fixture", out)
    assert out == []


# ---- the gate itself -------------------------------------------------------

@pytest.mark.lint
def test_repo_is_clean():
    """The tier-1 lint node: the full analyzer (static rules + jaxpr-drift
    tracing) over the repo must report nothing outside the baseline —
    exactly what ``python -m matchmaking_tpu.analysis`` gates in CI."""
    new, _accepted, warnings = analyze_repo()
    assert not warnings, "\n".join(warnings)
    assert not new, "matchlint findings:\n" + "\n".join(
        f.render() for f in new)


def test_determinism_covers_deadline_propagation_arithmetic():
    """ISSUE 5 satellite: the rule covers the overload subsystem's new
    deadline shapes — header-subscript stores, aug-assigns, and
    deadline= keyword arguments computed from time.time()."""
    findings = analyze_source('''
import time

def faults(headers, submit):
    headers["x-deadline"] = time.time() + 5.0
    deadline = 10.0
    deadline += time.time()
    submit(deadline=time.time() + 1.0)
''', path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["determinism"] * 3
    # The sanctioned shape: the one wall-clock read is a plain argument
    # and every derivation takes `now` as a parameter (overload.py).
    clean = analyze_source('''
def stamp_deadline(headers, now, budget_s):
    headers.setdefault("x-deadline", repr(now + budget_s))

def check(headers, now):
    raw = headers.get("x-deadline")
    return raw is not None and now >= float(raw)
''', path="matchmaking_tpu/service/fixture.py")
    assert clean == []


def test_determinism_covers_snapshot_interval_arithmetic():
    """ISSUE 6 satellite: the continuous-telemetry sampler added a
    schedule-shaped surface — next-snapshot / sample-due arithmetic born
    from time.time() is the same replay hazard as deadline math. The
    sanctioned shapes are asyncio.sleep cadence (no stored wake time) or
    time.monotonic(); time.time() stays legal as snapshot DATA."""
    findings = analyze_source('''
import time

class Sampler:
    def schedule(self, interval):
        self._next_snapshot = time.time() + interval
        sample_due = time.time() + interval
        if time.time() >= self._next_snapshot:
            return True
''', path="matchmaking_tpu/utils/fixture.py")
    assert _rules(findings) == ["determinism"] * 3
    clean = analyze_source('''
import time

class Sampler:
    def sample(self, ring):
        # wall clock as DATA (the ring timestamp), monotonic for cadence
        ring.append(time.time(), {"x": 1.0})
        self._next_snapshot = time.monotonic() + 1.0
''', path="matchmaking_tpu/utils/fixture.py")
    assert clean == []


def test_cross_class_guarded_by_checks_external_serialization():
    """ISSUE 7 satellite (PR 4 carry-over): a class declaring
    ``externally-serialized-by: <lock>`` arms method-CALL checking on
    every attribute guarded by that lock — an off-lock
    ``self.engine.remove(...)`` is now a finding, not a docstring
    violation; declared ``lock-free:`` reads stay exempt."""
    src = '''
import asyncio

# externally-serialized-by: _engine_lock
# lock-free: pool_size
class FakeEngine:
    def expire_deadlines(self, now):
        return []

    def pool_size(self):
        return 0

class Runtime:
    def __init__(self):
        self._engine_lock = asyncio.Lock()
        # guarded-by: _engine_lock
        self.engine = FakeEngine()

    async def bad(self, now):
        return self.engine.expire_deadlines(now)

    async def good_read(self):
        return self.engine.pool_size()

    async def good_locked(self, now):
        async with self._engine_lock:
            return self.engine.expire_deadlines(now)

    # holds-lock: _engine_lock
    def good_helper(self, now):
        return self.engine.expire_deadlines(now)
'''
    findings = analyze_source(src, path="matchmaking_tpu/service/fixture.py")
    guarded = [f for f in findings if f.rule == "guarded-by"]
    assert len(guarded) == 1
    assert "Runtime.bad" in guarded[0].context
    assert "externally-serialized-by" in guarded[0].message
    # Without the class declaration, calls through the attr are unchecked
    # (the pre-cross-class behavior — only mutations/stores were).
    undeclared = src.replace(
        "# externally-serialized-by: _engine_lock\n", "").replace(
        "# lock-free: pool_size\n", "")
    assert [f for f in analyze_source(
        undeclared, path="matchmaking_tpu/service/fixture.py")
        if f.rule == "guarded-by"] == []


def test_determinism_covers_edf_ordering_arithmetic():
    """ISSUE 7 satellite: the EDF window-cut ordering keys are a new
    schedule-shaped surface — a cut key born from time.time() makes
    window COMPOSITION depend on scheduler jitter. The sanctioned shape
    is a pure function of the message (stamped x-deadline header + the
    admission-cached delivery tier)."""
    findings = analyze_source('''
import time

def cut(pending, delivery):
    edf_key = (delivery.tier, time.time() + 0.2)
    cut_key = time.time() + 1.0
    return sorted(pending, key=lambda d: edf_key)
''', path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["determinism"] * 2
    clean = analyze_source('''
def edf_key(item, deadline_of):
    _req, delivery = item
    deadline = deadline_of(delivery.properties.headers)
    return (delivery.tier,
            deadline if deadline is not None else float("inf"))
''', path="matchmaking_tpu/service/fixture.py")
    assert clean == []


# ---- perf (ISSUE 8: O(pool)/O(matches) scans on the hot path) --------------

def test_perf_flags_pool_scan_in_hot_path_function():
    """A for-loop over a pool mirror column inside a hot-path-named
    function is the O(pool) wall the columnar path exists to avoid."""
    findings = analyze_source('''
class Engine:
    def _flush_window(self, now):
        total = 0.0
        for r in self.pool.m_rating:
            total += r
        return total
''', path="matchmaking_tpu/engine/fixture.py")
    assert _rules(findings) == ["perf"]
    assert "m_rating" in findings[0].message


def test_perf_flags_waiting_scan_and_full_column_asarray():
    findings = analyze_source('''
import numpy as np

class Engine:
    def _dispatch_cols(self, cols, now):
        ages = [now - r.enqueued_at for r in self.engine.waiting()]
        col = np.asarray(self.pool.m_enqueued)
        return ages, col
''', path="matchmaking_tpu/engine/fixture.py")
    assert sorted(_rules(findings)) == ["perf", "perf"]


def test_perf_flags_request_at_inside_loop():
    findings = analyze_source('''
class Engine:
    def _finalize_window(self, slots):
        return [self.pool.request_at(s) for s in slots]
''', path="matchmaking_tpu/engine/fixture.py")
    assert _rules(findings) == ["perf"]
    assert "request_at" in findings[0].message


def test_perf_accepts_vectorized_hot_path_and_cold_scans():
    """Indexed column reads (col[slots]) are the sanctioned vectorized
    form; window-sized loops are fine; and the same scan OUTSIDE a
    hot-path-named function (sweepers, eviction policy) is out of scope."""
    clean = analyze_source('''
import numpy as np

class Engine:
    def _finalize_columnar(self, qs, now):
        eff = np.maximum(0.0, now - self.pool.m_enqueued[qs])
        ids = self.pool.m_id[qs]
        return eff, ids

    def _flush_inner(self, window):
        return [req for req, _d in window]

    def _evict_policy(self):
        return sorted(self.engine.waiting(), key=lambda r: r.enqueued_at)
''', path="matchmaking_tpu/engine/fixture.py")
    assert clean == []


def test_perf_flags_per_delivery_header_parse_in_hot_loop():
    """ISSUE 9: a headers[...] subscript or headers.get(...) call inside a
    loop in a hot-path function is per-delivery wire work the
    window-granular path removed — parse once at admission, cache on the
    Delivery."""
    findings = analyze_source('''
class Runtime:
    def _flush_columnar(self, deliveries, now):
        tiers = []
        for d in deliveries:
            tiers.append(int(d.properties.headers["x-tier"]))
        return tiers

    def _handle_columnar_out(self, out, deliveries, now):
        return [d.properties.headers.get("x-deadline") for d in deliveries]
''', path="matchmaking_tpu/service/fixture.py")
    assert sorted(_rules(findings)) == ["perf", "perf"]
    assert "header parse" in findings[0].message
    # The cached read (no header touch) is the sanctioned form.
    clean = analyze_source('''
class Runtime:
    def _flush_columnar(self, deliveries, now):
        return [(d.tier, d.deadline) for d in deliveries]

    def _on_delivery(self, delivery):
        # Not hot-path-named: the once-per-delivery admission parse site.
        return delivery.properties.headers.get("x-tier")
''', path="matchmaking_tpu/service/fixture.py")
    assert clean == []


def test_perf_flags_per_element_encode_response_in_hot_loop():
    """ISSUE 9: encode_response() per element inside _flush_*/_handle_*
    is the egress hot loop the native batch encoder replaced."""
    findings = analyze_source('''
from matchmaking_tpu.service.contract import encode_response

class Runtime:
    def _handle_columnar_out(self, out, responses):
        return [encode_response(r) for r in responses]
''', path="matchmaking_tpu/service/fixture.py")
    assert _rules(findings) == ["perf"]
    assert "encode_response" in findings[0].message
    # Outside a loop (one-off response) it is fine, as is the batch call.
    clean = analyze_source('''
from matchmaking_tpu.service.contract import encode_response
from matchmaking_tpu.native import codec

class Runtime:
    def _handle_columnar_out(self, out, resp, rows):
        bodies = codec.encode_simple_batch(*rows)
        return encode_response(resp)
''', path="matchmaking_tpu/service/fixture.py")
    assert clean == []


def test_perf_inline_ignore_with_reason_suppresses():
    body = '''
class Engine:
    def _finalize_window(self, slots):
        return [self.pool.request_at(s) for s in slots]  # matchlint: ignore[perf] object path by contract
'''
    assert analyze_source(
        body, path="matchmaking_tpu/engine/fixture.py") == []
