"""HTTP observability endpoint: /healthz, /metrics, /debug/* (SURVEY.md §5
"Metrics/logging/observability").

The reference leans on BEAM introspection; the rebuild exposes the service's
counters/latencies over a tiny aiohttp server (aiohttp is in the base image —
SURVEY.md §7 [ENV]). Surfaces:

- ``/healthz`` — liveness + per-queue pool occupancy, live engine class,
  breaker state (``status: degraded`` while any breaker is open).
- ``/metrics`` — JSON report; ``?format=prom`` renders valid Prometheus
  exposition text (one ``# TYPE`` per family, escaped label values, and the
  per-stage latency histograms as a real histogram family:
  ``matchmaking_stage_seconds_bucket{queue=...,stage=...,le=...}``).
- ``/debug/traces`` — the request-lifecycle flight recorder (utils/trace.py):
  recent settled traces + slow exemplars per queue (``?queue=`` filter,
  ``?id=`` single-trace lookup).
- ``/debug/events`` — the lifecycle event timeline (breaker trips, probes,
  delegations, re-promotions, revives, chaos faults; ``?queue=``/``?n=``).
- ``/debug/attribution`` — critical-path attribution (service/attribution):
  per-queue wait-vs-work decomposition of settled spans, device idle
  fraction, SLO burn state, and the p99 exemplar's exact gap waterfall.
- ``/debug/quality`` — the match-quality & fairness observatory (ISSUE 8):
  per-queue/per-tier quality + wait-at-match histograms, per-rating-bucket
  conditional means, disparity gaps, quality-SLO burn state.
- ``/debug/telemetry`` — the continuous telemetry ring
  (utils/timeseries.py): periodic snapshots with ``?n=``/``?key=`` filters.
- ``/debug/profile?secs=N`` — a jax.profiler capture of the live serving
  process (returns the trace directory; view with TensorBoard/XProf).
"""

from __future__ import annotations

import json
import time
from typing import Any

try:
    from aiohttp import web
except ImportError:  # pragma: no cover - aiohttp is in the base image
    web = None


def build_report(app) -> dict[str, Any]:
    """The full /metrics JSON payload for a MatchmakingApp — module-level so
    non-HTTP consumers (bench.py snapshots its final report into the BENCH
    json) share one report shape with the endpoint."""
    report = app.metrics.report()
    report["pools"] = {
        name: rt.engine.pool_size()
        for name, rt in app._runtimes.items()
    }
    # Dedup-cache occupancy (round-4 verdict weak #7: the cache is
    # size-gated + TTL-pruned but its growth was invisible — a long
    # dedup_ttl_s under a high match rate holds one TTL's worth of
    # encoded bodies per queue). Via the public accessor, not the
    # private dict (ADVICE round-5 #5).
    report["dedup_cache"] = {
        name: rt.dedup_cache_size()
        for name, rt in app._runtimes.items()
        if hasattr(rt, "dedup_cache_size")
    }
    report["broker"] = dict(app.broker.stats)
    # Engine lifecycle counters (e.g. team_delegated/team_repromoted:
    # the wildcard delegation round-trip must be visible, not silent).
    counters = {
        name: dict(rt.engine.counters)
        for name, rt in app._runtimes.items()
        if getattr(rt.engine, "counters", None)
    }
    if counters:
        report["engine_counters"] = counters
    # Circuit-breaker state (service/breaker.py): live snapshots so
    # time_degraded_s includes the current open stretch, not just the
    # gauge written at the last transition.
    now = time.time()
    breakers = {
        name: rt.breaker.snapshot(now)
        for name, rt in app._runtimes.items()
        if getattr(rt, "breaker", None) is not None
    }
    if breakers:
        report["breakers"] = breakers
    # Overload admission control (service/overload.py): credits held,
    # adaptive credit fraction, shed/expired totals, drain state — the
    # shed story must be readable from /metrics alone.
    overload = {
        name: rt.admission.snapshot()
        for name, rt in app._runtimes.items()
        if getattr(rt, "admission", None) is not None
    }
    if overload:
        report["overload"] = overload
    # Device-utilization counters (ISSUE 6): monotone busy/idle seconds +
    # h2d/step/readback split + effective occupancy per device-engine
    # queue — idle FRACTION over any interval is a delta of two scrapes.
    util = {
        name: rt.engine.util_report()
        for name, rt in app._runtimes.items()
        if hasattr(rt.engine, "util_report")
    }
    if util:
        report["device_util"] = util
    # Crash durability (ISSUE 15): per-queue journal accounting (live
    # seq, segment growth, lifetime write amplification) + the last
    # hard-crash recovery record — the RTO story must be readable from
    # /metrics alone.
    durability = {
        name: {
            "seq": rt.journal.seq,
            "synced_seq": rt.journal.synced_seq,
            "fsync": rt.journal.fsync,
            "segment_records": rt.journal.segment_records,
            "segment_bytes": rt.journal.segment_bytes,
            "bytes_written": rt.journal.bytes_written,
            "payload_bytes": rt.journal.payload_bytes,
            "write_amplification": (
                round(rt.journal.bytes_written
                      / rt.journal.payload_bytes, 3)
                if rt.journal.payload_bytes else None),
            "last_recovery": rt.last_recovery,
        }
        for name, rt in app._runtimes.items()
        if getattr(rt, "journal", None) is not None
    }
    if durability:
        report["durability"] = durability
    # Hot-standby replication (ISSUE 17): per-queue role/epoch/watermark
    # block — the failover story (who owns the queue, how far behind the
    # standby is, what a host loss right now would cost) must be readable
    # from /metrics alone, like the RTO story above.
    replication = {
        name: rt.replication.snapshot()
        for name, rt in app._runtimes.items()
        if getattr(rt, "replication", None) is not None
    }
    if replication:
        report["replication"] = replication
    # Critical-path attribution + SLO burn state (ISSUE 6).
    attribution = getattr(app, "attribution", None)
    if attribution is not None:
        report["attribution"] = attribution.snapshot()
    slo = {
        name: mon.snapshot()
        for name, mon in getattr(app, "_slo_monitors", {}).items()
    }
    if slo:
        report["slo"] = slo
    telemetry = getattr(app, "telemetry", None)
    if telemetry is not None:
        latest = telemetry.latest()
        if latest is not None:
            report["telemetry_last"] = latest
    # Match-quality & fairness (ISSUE 8): the service-level per-queue/
    # per-tier ledger plus each engine's per-rating-bucket report (device
    # accumulator snapshot / host equivalent — lock-free cached reads).
    quality = getattr(app, "quality", None)
    if quality is not None:
        report["quality"] = quality.snapshot()
    engine_quality = {}
    for name, rt in app._runtimes.items():
        rep = (rt.engine.quality_report()
               if hasattr(rt.engine, "quality_report") else None)
        if rep is not None:
            engine_quality[name] = rep
    if engine_quality:
        report["quality_engine"] = engine_quality
    # Incident forensics (ISSUE 18): capture/drop counters, per-class
    # split and bundle summaries — the black box must be discoverable
    # from /metrics (and prom, via the incidents_* counters) without
    # knowing /debug/incidents exists.
    incidents = getattr(app, "incidents", None)
    if incidents is not None:
        report["incidents"] = incidents.snapshot()
    return report


def _esc(value: Any) -> str:
    """Prometheus label-value escaping (exposition format spec: backslash,
    double-quote and newline must be escaped inside quoted label values)."""
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt_labels(labels: dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
    return "{" + inner + "}"


class _PromFamilies:
    """Collects samples grouped by metric family so the exposition text
    carries exactly ONE ``# TYPE`` line per family, before its samples —
    the spec rule the old flattener broke (missing TYPE for breaker/pool/
    dedup/engine families; duplicated TYPE per label set elsewhere)."""

    def __init__(self) -> None:
        self._fams: dict[str, tuple[str, list[str]]] = {}

    def add(self, family: str, mtype: str, labels: dict[str, Any],
            value: Any, suffix: str = "") -> None:
        fam = self._fams.get(family)
        if fam is None:
            fam = self._fams[family] = (mtype, [])
        fam[1].append(f"{family}{suffix}{_fmt_labels(labels)} {value}")

    def render(self) -> str:
        lines: list[str] = []
        for family in sorted(self._fams):
            mtype, samples = self._fams[family]
            lines.append(f"# TYPE {family} {mtype}")
            lines.extend(samples)
        return "\n".join(lines) + "\n"


def _flatten_prom(report: dict[str, Any]) -> str:
    """Report dict → valid Prometheus exposition text."""
    fams = _PromFamilies()
    for name, value in report.get("counters", {}).items():
        fams.add(f"matchmaking_{name}", "counter", {}, value)
    for name, value in report.get("gauges", {}).items():
        # Gauge names may carry a [queue] suffix → a prom label; several
        # queues then share ONE family (and its single TYPE line).
        base, _, queue = name.partition("[")
        labels = {"queue": queue.rstrip("]")} if queue else {}
        fams.add(f"matchmaking_{base}", "gauge", labels, value)
    for queue, snap in report.get("breakers", {}).items():
        for stat in ("trips", "probes", "probe_failures"):
            fams.add(f"matchmaking_breaker_{stat}", "counter",
                     {"queue": queue}, snap[stat])
    for series, summary in report.get("latency", {}).items():
        for stat, value in summary.items():
            fams.add(f"matchmaking_{series}_{stat}", "gauge", {}, value)
    for queue, depth in report.get("pools", {}).items():
        fams.add("matchmaking_pool_size", "gauge", {"queue": queue}, depth)
    for queue, size in report.get("dedup_cache", {}).items():
        fams.add("matchmaking_dedup_cache_size", "gauge",
                 {"queue": queue}, size)
    for queue, counters in report.get("engine_counters", {}).items():
        for stat, value in counters.items():
            fams.add(f"matchmaking_engine_{stat}", "counter",
                     {"queue": queue}, value)
    # Device utilization (monotone counters + one gauge): idle fraction
    # between any two scrapes is delta(idle) / delta(busy + idle).
    for queue, u in report.get("device_util", {}).items():
        fams.add("matchmaking_device_busy_seconds", "counter",
                 {"queue": queue}, u["device_busy_s"])
        fams.add("matchmaking_device_idle_seconds", "counter",
                 {"queue": queue}, u["device_idle_s"])
        fams.add("matchmaking_device_readback_seconds", "counter",
                 {"queue": queue}, u["readback_s"])
        fams.add("matchmaking_device_effective_occupancy", "gauge",
                 {"queue": queue}, u["effective_occupancy"])
    # Attribution work/wait: cumulative seconds per queue and per category
    # (counters — rate() in PromQL gives the live wait-vs-work split).
    for queue, entry in report.get("attribution", {}).get("queues",
                                                          {}).items():
        fams.add("matchmaking_attributed_work_seconds", "counter",
                 {"queue": queue}, entry["work_s"])
        fams.add("matchmaking_attributed_wait_seconds", "counter",
                 {"queue": queue}, entry["wait_s"])
        for cat, c in entry.get("categories", {}).items():
            fams.add("matchmaking_attribution_seconds", "counter",
                     {"queue": queue, "category": cat, "kind": c["kind"]},
                     c["total_s"])
        # Per-QoS-tier work/wait split (tiered serving): cumulative, so
        # rate() gives the live per-tier wait fraction.
        for t, ts in entry.get("tiers", {}).items():
            fams.add("matchmaking_attributed_work_seconds", "counter",
                     {"queue": queue, "tier": t}, ts["work_s"])
            fams.add("matchmaking_attributed_wait_seconds", "counter",
                     {"queue": queue, "tier": t}, ts["wait_s"])
        rescan = entry.get("rescan")
        if rescan:
            fams.add("matchmaking_rescan_attributed_seconds", "counter",
                     {"queue": queue}, rescan["total_s"])
            fams.add("matchmaking_rescan_windows", "counter",
                     {"queue": queue}, rescan["windows"])
    # Match-quality & fairness families (ISSUE 8). Per-queue/per-tier
    # quality histogram from the service ledger…
    q_meta = report.get("quality", {})
    n_q = int(q_meta.get("quality_buckets", 0) or 0)
    for queue, entry in q_meta.get("queues", {}).items():
        for tier, tq in entry.get("tiers", {}).items():
            labels = {"queue": queue, "tier": tier}
            counts = tq.get("quality_hist", [])
            cum = 0
            for k, c in enumerate(counts):
                cum += int(c)
                le = format((k + 1) / max(1, n_q or len(counts)), ".6g")
                fams.add("matchmaking_match_quality", "histogram",
                         {**labels, "le": le}, cum, suffix="_bucket")
            fams.add("matchmaking_match_quality", "histogram",
                     {**labels, "le": "+Inf"}, cum, suffix="_bucket")
            fams.add("matchmaking_match_quality", "histogram", labels,
                     tq.get("quality_sum", 0.0), suffix="_sum")
            fams.add("matchmaking_match_quality", "histogram", labels,
                     tq.get("count", 0), suffix="_count")
    # …and the per-RATING-BUCKET wait-at-match histogram from the engine
    # accumulators (the fairness axis), plus the disparity gauges.
    for queue, rep in report.get("quality_engine", {}).items():
        for b in rep.get("buckets", ()):
            if not b.get("count"):
                continue
            labels = {"queue": queue, "bucket": b["bucket"]}
            for le, cum in b.get("wait_le", {}).items():
                fams.add("matchmaking_wait_at_match_seconds", "histogram",
                         {**labels, "le": le}, cum, suffix="_bucket")
            fams.add("matchmaking_wait_at_match_seconds", "histogram",
                     labels, b.get("wait_sum_s", 0.0), suffix="_sum")
            fams.add("matchmaking_wait_at_match_seconds", "histogram",
                     labels, b["count"], suffix="_count")
            fams.add("matchmaking_bucket_quality_mean", "gauge", labels,
                     b.get("quality_mean") or 0.0)
        disp = rep.get("disparity", {})
        fams.add("matchmaking_quality_disparity", "gauge", {"queue": queue},
                 disp.get("quality_gap", 0.0))
        fams.add("matchmaking_wait_p90_disparity_seconds", "gauge",
                 {"queue": queue}, disp.get("wait_p90_gap_s", 0.0))
        if rep.get("quality_mean") is not None:
            fams.add("matchmaking_quality_mean", "gauge", {"queue": queue},
                     rep["quality_mean"])
    # Incident forensics (ISSUE 18): the aggregate incidents_captured /
    # incidents_dropped counters already flow through the counters block
    # above; the per-trigger-class split gets its own labeled family so
    # alert rules can page on "failover captured a bundle" specifically.
    for cls, n in report.get("incidents", {}).get("by_class", {}).items():
        fams.add("matchmaking_incidents_by_class", "counter",
                 {"class": cls}, n)
    # True per-stage latency histograms (the flight recorder's output) as a
    # proper histogram family: cumulative le buckets + _sum + _count.
    for queue, stages in report.get("stage_seconds", {}).items():
        for stage, hist in stages.items():
            labels = {"queue": queue, "stage": stage}
            for le, cum in hist["le"].items():
                fams.add("matchmaking_stage_seconds", "histogram",
                         {**labels, "le": le}, cum, suffix="_bucket")
            fams.add("matchmaking_stage_seconds", "histogram", labels,
                     hist["sum_s"], suffix="_sum")
            fams.add("matchmaking_stage_seconds", "histogram", labels,
                     hist["count"], suffix="_count")
    return fams.render()


class ObservabilityServer:
    """Owns the aiohttp runner; start()/stop() from the app's event loop."""

    def __init__(self, app, host: str = "127.0.0.1", port: int = 9100):
        if web is None:
            raise RuntimeError("aiohttp unavailable: observability disabled")
        self.app = app
        self.host = host
        self.port = port
        self._runner: Any = None
        self._site: Any = None
        self._profiling = False
        #: One capture directory per server lifetime (jax writes each
        #: start/stop_trace cycle into its own timestamped subdir) — a
        #: fresh mkdtemp per request would leak directories forever.
        self._profile_dir = ""

    def _report(self) -> dict[str, Any]:
        return build_report(self.app)

    async def _healthz(self, request) -> "web.Response":
        now = time.time()
        queues: dict[str, Any] = {}
        degraded: list[str] = []
        for name, rt in self.app._runtimes.items():
            entry: dict[str, Any] = {
                "backend": rt.app.cfg.engine.backend,
                # The LIVE engine class, not the configured backend: a
                # breaker-demoted queue reports the host oracle it is
                # actually running on.
                "engine": type(rt.engine).__name__,
                "pool_size": rt.engine.pool_size(),
                "team_size": rt.queue_cfg.team_size,
            }
            breaker = getattr(rt, "breaker", None)
            if breaker is not None:
                entry["breaker"] = breaker.snapshot(now)
                if breaker.state != "closed":
                    degraded.append(name)
            admission = getattr(rt, "admission", None)
            if admission is not None:
                entry["overload"] = admission.snapshot()
            monitors = getattr(self.app, "_slo_monitors", {})
            monitor = monitors.get(name)
            if monitor is not None:
                entry["slo"] = monitor.snapshot()
            # Tiered QoS: the per-tier burn monitors (keyed "queue@tN") —
            # /healthz must show WHICH tier is burning, not an aggregate
            # that averages tier-0 holding with tier-2 burning on purpose.
            tier_mons = {k.rsplit("@", 1)[1]: m.snapshot()
                         for k, m in monitors.items()
                         if k.startswith(name + "@t")}
            if tier_mons:
                entry["slo_tiers"] = tier_mons
            # Quality SLO (ISSUE 8): GOOD = matched with quality >= target
            # — a quality regression burns here like a latency SLO.
            q_mon = monitors.get(name + "#quality")
            if q_mon is not None:
                entry["slo_quality"] = q_mon.snapshot()
            # Replication role + lag (ISSUE 17): a load balancer must see
            # "fenced" (stop routing here — the successor owns the queue)
            # and operators must see the lag watermark that bounds what a
            # host loss right now would cost.
            repl = getattr(rt, "replication", None)
            if repl is not None:
                entry["replication"] = {
                    "role": repl.role,
                    "epoch": repl.epoch,
                    "lag": repl.lag(),
                    "acked_seq": repl.acked_seq,
                    "sent_seq": repl.sent_seq,
                }
                if repl.role == "fenced":
                    degraded.append(name)
            queues[name] = entry
        # Burning keys include tier monitors ("queue@tN"): routing reacts
        # to the aggregate, placement/QoS tooling to the tier split.
        burning = [key for key, mon in
                   getattr(self.app, "_slo_monitors", {}).items()
                   if mon.burning]
        body = {
            # Degraded ≠ dead: matches still flow on the host path, so the
            # service stays live — operators alert on the field instead.
            # Draining trumps both: a load balancer must stop routing here.
            "status": ("draining" if any(
                q.get("overload", {}).get("draining") for q in queues.values())
                else "degraded" if degraded else "ok"),
            "degraded_queues": degraded,
            # SLO burn is orthogonal to liveness: a burning queue is up
            # but missing its latency objective — routing/placement acts
            # on this field, not on status.
            "slo_burning_queues": burning,
            "queues": queues,
        }
        # Black-box health (ISSUE 18): an operator triaging a page sees
        # "3 bundles captured, newest inc-000003" here and goes straight
        # to /debug/incidents instead of spelunking five rings.
        incidents = getattr(self.app, "incidents", None)
        if incidents is not None:
            inc = incidents.snapshot()
            body["incidents"] = {
                "captured": inc["captured"],
                "dropped": inc["dropped"],
                "by_class": inc["by_class"],
                "last_id": (inc["incidents"][-1]["id"]
                            if inc["incidents"] else None),
            }
        return web.json_response(body)

    async def _metrics(self, request) -> "web.Response":
        report = self._report()
        if request.query.get("format") == "prom":
            return web.Response(text=_flatten_prom(report),
                                content_type="text/plain")
        return web.Response(text=json.dumps(report, sort_keys=True),
                            content_type="application/json")

    async def _debug_traces(self, request) -> "web.Response":
        """Flight recorder: recent + slow-exemplar traces.
        ``?queue=`` filters; ``?id=`` looks one trace up; ``?n=`` caps the
        per-ring count (default 32)."""
        recorder = getattr(self.app, "recorder", None)
        if recorder is None or not getattr(self.app, "trace_enabled", True):
            # Distinguish "tracing off" from "no slow requests": an empty
            # ring on a disabled service would read as a clean bill of
            # health during a p99 incident.
            return web.json_response({"error": "tracing disabled"},
                                     status=404)
        trace_id = request.query.get("id")
        if trace_id:
            tr = recorder.get(trace_id)
            if tr is None:
                return web.json_response(
                    {"error": f"trace {trace_id!r} not found (rings are "
                              "bounded — it may have been evicted)"},
                    status=404)
            return web.json_response(tr.to_dict())
        try:
            limit = max(1, int(request.query.get("n", "32")))
        except ValueError:
            limit = 32
        return web.json_response(
            recorder.snapshot(queue=request.query.get("queue"), limit=limit))

    async def _debug_attribution(self, request) -> "web.Response":
        """Critical-path attribution (service/attribution.py): per-queue
        wait-vs-work decomposition of settled enqueue→publish spans —
        category sums/histogram p99s, the device idle fraction, SLO
        attainment, and the p99 EXEMPLAR trace's exact decomposition
        (its gap durations sum to its span by construction, so "X% of the
        p99 is wait behind the broker" is a number, not an inference).
        ``?queue=`` filters; ``?p=`` picks the exemplar percentile."""
        attribution = getattr(self.app, "attribution", None)
        if attribution is None or not getattr(self.app, "trace_enabled", True):
            return web.json_response({"error": "attribution disabled"},
                                     status=404)
        try:
            p = min(100.0, max(0.0, float(request.query.get("p", "99"))))
        except ValueError:
            p = 99.0
        body = attribution.snapshot(queue=request.query.get("queue"))
        from matchmaking_tpu.service.attribution import decompose

        for q, entry in body["queues"].items():
            rt = self.app._runtimes.get(q)
            if rt is not None and hasattr(rt.engine, "util_report"):
                entry["device_util"] = rt.engine.util_report()
            monitor = getattr(self.app, "_slo_monitors", {}).get(q)
            if monitor is not None:
                entry["slo"] = monitor.snapshot()
            exemplar = self.app.recorder.percentile_exemplar(q, p)
            if exemplar is not None:
                entry[f"p{p:g}_exemplar"] = decompose(exemplar)
        return web.json_response(body)

    async def _debug_quality(self, request) -> "web.Response":
        """Match-quality & fairness observatory (ISSUE 8): per queue —
        the service ledger's per-tier quality/wait histograms, the
        engine's per-rating-bucket conditional report (device accumulator
        snapshot or host equivalent — cached, never a device sync on the
        loop), the explicit disparity gaps, and the quality-SLO burn
        state. ``?queue=`` filters."""
        queue = request.query.get("queue") or None
        ledger = self.app.quality.snapshot(queue=queue)
        body: "dict[str, Any]" = {
            "quality_buckets": ledger["quality_buckets"],
            "wait_edges_s": ledger["wait_edges_s"],
            "queues": {},
        }
        monitors = getattr(self.app, "_slo_monitors", {})
        names = ([queue] if queue is not None
                 else sorted(self.app._runtimes))
        for name in names:
            rt = self.app._runtimes.get(name)
            if rt is None:
                continue
            entry: dict[str, Any] = {
                "service": ledger["queues"].get(name, {}),
            }
            rep = (rt.engine.quality_report()
                   if hasattr(rt.engine, "quality_report") else None)
            if rep is not None:
                entry["engine"] = rep
                entry["disparity"] = rep.get("disparity")
            mon = monitors.get(name + "#quality")
            if mon is not None:
                entry["slo_quality"] = mon.snapshot()
            body["queues"][name] = entry
        return web.json_response(body)

    async def _debug_placement(self, request) -> "web.Response":
        """Elastic placement control plane (ISSUE 11): current queue →
        device bindings (shard degree, generation, typestate), the
        decision audit ring — each record with the signal snapshot that
        drove it and the measured migration blackout — per-queue blackout
        stats, and the cross-queue dispatch arbiter's engagement state.
        ``?n=`` caps the decision history (default: the full ring)."""
        ctrl = getattr(self.app, "placement", None)
        # Hierarchical-formation state (ISSUE 14): per-queue bucket
        # occupancy, the adaptive frontier-K choice + move ring, and the
        # touched-slot fraction — placement-adjacent capacity data, so it
        # rides this surface whether or not the controller is enabled.
        formation = {
            name: rep
            for name, rt in self.app._runtimes.items()
            if (rep := (rt.engine.formation_report()
                        if hasattr(rt.engine, "formation_report")
                        else None)) is not None
        }
        # Device-loss failover audit (ISSUE 15): D -> D-1 demotions with
        # the measured blackout, plus each queue's LIVE binding — a
        # failover re-binds behind the controller's back, so the audited
        # truth lives here whether or not the control plane is enabled.
        failover = {
            name: {"binding": (list(rt.placement)
                               if rt.placement is not None else None),
                   "demotions": list(rt.failover_log)}
            for name, rt in self.app._runtimes.items()
            if rt.failover_log
        }
        if ctrl is None:
            if formation or failover:
                body = {}
                if formation:
                    body["formation"] = formation
                if failover:
                    body["failover"] = failover
                return web.json_response(body)
            return web.json_response(
                {"error": "placement control plane disabled "
                          "(set placement.interval_s)"}, status=404)
        try:
            history = max(0, int(request.query.get("n", "0")))
        except ValueError:
            history = 0
        body = ctrl.snapshot(history=history)
        if formation:
            body["formation"] = formation
        if failover:
            body["failover"] = failover
        return web.json_response(body)

    async def _debug_autotune(self, request) -> "web.Response":
        """Online autotuner (ISSUE 13): the steering target, declared safe
        ranges, current live knob values per queue, and the knob-decision
        audit ring — each record with the driving signal snapshot and the
        observed effect one tick later. ``?n=`` caps the decision history
        (default: the full ring)."""
        tuner = getattr(self.app, "autotune", None)
        if tuner is None:
            return web.json_response(
                {"error": "autotuner disabled (set autotune.interval_s)"},
                status=404)
        try:
            history = max(0, int(request.query.get("n", "0")))
        except ValueError:
            history = 0
        return web.json_response(tuner.snapshot(history=history))

    async def _debug_telemetry(self, request) -> "web.Response":
        """The continuous telemetry ring (utils/timeseries.py): ``?n=``
        tail length, ``?key=`` comma-separated key-prefix filter
        (``idle_frac`` matches every queue's ``idle_frac[q]`` series)."""
        telemetry = getattr(self.app, "telemetry", None)
        if telemetry is None:
            return web.json_response({"error": "telemetry disabled"},
                                     status=404)
        try:
            limit = int(request.query.get("n", "0"))
        except ValueError:
            limit = 0
        prefixes = tuple(k for k in request.query.get("key", "").split(",")
                         if k)
        return web.json_response({
            "snapshots": telemetry.snapshot(limit=limit, prefixes=prefixes)})

    async def _debug_events(self, request) -> "web.Response":
        """Lifecycle event timeline (``?queue=`` filter, ``?n=`` tail)."""
        events = getattr(self.app, "events", None)
        if events is None:
            return web.json_response({"error": "event log disabled"},
                                     status=404)
        try:
            limit = int(request.query.get("n", "0"))
        except ValueError:
            limit = 0
        return web.json_response({
            "events": events.snapshot(queue=request.query.get("queue"),
                                      limit=limit)})

    async def _debug_incidents(self, request) -> "web.Response":
        """Black-box bundles (ISSUE 18): summaries + capture/drop
        counters; ``?id=inc-NNNNNN`` fetches one full bundle,
        ``?bundles=1`` inlines them all (incident-soak convenience —
        bundles are bounded but not small)."""
        incidents = getattr(self.app, "incidents", None)
        if incidents is None or not self.app.cfg.forensics.enabled():
            return web.json_response({"error": "incident capture disabled"},
                                     status=404)
        incident_id = request.query.get("id")
        if incident_id:
            bundle = incidents.get(incident_id)
            if bundle is None:
                return web.json_response(
                    {"error": f"no incident {incident_id!r} in the ring"},
                    status=404)
            return web.Response(text=json.dumps(bundle, sort_keys=True),
                                content_type="application/json")
        body = incidents.snapshot(
            include_bundles=request.query.get("bundles") == "1")
        return web.Response(text=json.dumps(body, sort_keys=True),
                            content_type="application/json")

    async def _debug_profile(self, request) -> "web.Response":
        """jax.profiler capture of the live process: ``?secs=N`` (clamped to
        30 s). One capture at a time — the profiler is process-global."""
        if self._profiling:
            return web.json_response(
                {"error": "a profile capture is already running"}, status=409)
        try:
            secs = min(max(0.05, float(request.query.get("secs", "2"))), 30.0)
        except ValueError:
            return web.json_response({"error": "secs must be a number"},
                                     status=400)
        try:
            import jax
        except Exception as e:  # pragma: no cover - jax is in the image
            return web.json_response({"error": f"jax unavailable: {e}"},
                                     status=501)
        trace_dir = (getattr(self.app.cfg.observability, "profile_dir", "")
                     or self._profile_dir)
        if not trace_dir:
            import tempfile

            trace_dir = self._profile_dir = tempfile.mkdtemp(
                prefix="mm_profile_")
        self._profiling = True
        try:
            jax.profiler.start_trace(trace_dir)
            try:
                # The event loop keeps serving traffic during the capture —
                # that traffic IS what the profile is for.
                import asyncio

                await asyncio.sleep(secs)
            finally:
                jax.profiler.stop_trace()
        except Exception as e:
            return web.json_response({"error": f"profiler failed: {e}"},
                                     status=500)
        finally:
            self._profiling = False
        return web.json_response({"trace_dir": trace_dir, "secs": secs,
                                  "viewer": "tensorboard --logdir "
                                            + trace_dir})

    async def start(self) -> None:
        http_app = web.Application()
        http_app.router.add_get("/healthz", self._healthz)
        http_app.router.add_get("/metrics", self._metrics)
        http_app.router.add_get("/debug/traces", self._debug_traces)
        http_app.router.add_get("/debug/attribution", self._debug_attribution)
        http_app.router.add_get("/debug/quality", self._debug_quality)
        http_app.router.add_get("/debug/placement", self._debug_placement)
        http_app.router.add_get("/debug/autotune", self._debug_autotune)
        http_app.router.add_get("/debug/telemetry", self._debug_telemetry)
        http_app.router.add_get("/debug/events", self._debug_events)
        http_app.router.add_get("/debug/incidents", self._debug_incidents)
        http_app.router.add_get("/debug/profile", self._debug_profile)
        self._runner = web.AppRunner(http_app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self.host, self.port)
        await self._site.start()

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
