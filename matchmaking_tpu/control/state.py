"""Placement state model: bindings, the migration typestate, the audit log.

The state object is the control plane's single source of truth for *where
every queue runs* (device ids + shard degree D) and *what the controller
did about it* (a bounded ring of :class:`PlacementDecision` records, each
carrying the signal snapshot that drove it and the measured blackout).

Exactly-once migration typestate: a queue is either ``STABLE`` or
``MIGRATING``; ``begin()`` refuses a second concurrent action on the same
queue (the executor's drain already serializes on the engine lock, but the
typestate makes the controller's own reentrancy bug a loud error instead
of a double drain).  ``complete()``/``fail()`` are the only exits — the
same acquire/settle discipline matchlint's settlement rule proves on the
delivery lifecycle, applied to placement actions.

Event-loop-confined like the batcher and the admission controller: all
mutation happens on the controller's tick (or the executor it awaits), so
there is deliberately no lock here.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Iterable

#: Queue placement statuses (the migration typestate).
STABLE = "stable"
MIGRATING = "migrating"

#: Decision kinds.
MIGRATE = "migrate"
PROMOTE = "promote"
DEMOTE = "demote"


class PlacementError(RuntimeError):
    """A placement-typestate violation (concurrent action on one queue,
    unknown queue/device, malformed target)."""


@dataclasses.dataclass
class QueuePlacement:
    """Where one queue runs: the bound logical device ids (shard degree D
    is their count) plus the migration typestate."""

    queue: str
    devices: tuple[int, ...]
    status: str = STABLE
    #: Monotone per-queue binding generation — bumped on every completed
    #: action, so an audit reader can order rebinding races out.
    generation: int = 0
    #: ``now`` of the last completed action (cooldown anchor; 0 = never).
    last_action_t: float = 0.0

    @property
    def shard(self) -> int:
        return len(self.devices)

    def to_dict(self) -> dict[str, Any]:
        return {
            "devices": list(self.devices),
            "shard": self.shard,
            "status": self.status,
            "generation": self.generation,
            "last_action_t": round(self.last_action_t, 3),
        }


@dataclasses.dataclass
class PlacementDecision:
    """One audit record: what the controller decided, on which signals,
    and what it cost."""

    seq: int
    t: float
    kind: str                       # migrate | promote | demote
    queue: str
    src: tuple[int, ...]
    dst: tuple[int, ...]
    #: The signal snapshot that drove the decision (policy view rows).
    signals: dict[str, Any] = dataclasses.field(default_factory=dict)
    status: str = "pending"         # pending | applied | failed
    #: Measured migration blackout (seconds the queue's engine lock was
    #: held across drain→restore; 0 until applied).
    blackout_s: float = 0.0
    #: Waiting players carried across the move.
    transferred: int = 0
    detail: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "seq": self.seq,
            "t": round(self.t, 3),
            "kind": self.kind,
            "queue": self.queue,
            "from": list(self.src),
            "to": list(self.dst),
            "signals": self.signals,
            "status": self.status,
            "blackout_ms": round(self.blackout_s * 1e3, 3),
            "transferred": self.transferred,
            "detail": self.detail,
        }


class PlacementState:
    """Bindings for every placed queue + the decision audit ring."""

    def __init__(self, n_devices: int, decision_ring: int = 256):
        if n_devices < 1:
            raise PlacementError(f"device inventory must be >= 1, "
                                 f"got {n_devices}")
        self.n_devices = n_devices
        self._placements: dict[str, QueuePlacement] = {}
        self.decisions: deque[PlacementDecision] = deque(
            maxlen=max(1, decision_ring))
        self._seq = 0
        #: Blackout stats per queue (max/last, seconds) — the bounded-
        #: blackout acceptance reads these without replaying the ring.
        self.blackout_last: dict[str, float] = {}
        self.blackout_max: dict[str, float] = {}

    # ---- bindings ----------------------------------------------------------

    def bind(self, queue: str, devices: Iterable[int]) -> QueuePlacement:
        """Initial binding (boot). Re-binding an existing queue resets it
        (the app rebuilds runtimes only at boot)."""
        devs = self._validate(devices)
        p = QueuePlacement(queue=queue, devices=devs)
        self._placements[queue] = p
        return p

    def placement(self, queue: str) -> QueuePlacement:
        try:
            return self._placements[queue]
        except KeyError:
            raise PlacementError(f"unplaced queue {queue!r}") from None

    def placements(self) -> dict[str, QueuePlacement]:
        return dict(self._placements)

    def queues_on(self, device: int) -> list[str]:
        """Queues bound to (sharing) one device, sorted for determinism."""
        return sorted(q for q, p in self._placements.items()
                      if device in p.devices)

    def free_devices(self) -> list[int]:
        """Devices with no queue bound, ascending."""
        used = {d for p in self._placements.values() for d in p.devices}
        return [d for d in range(self.n_devices) if d not in used]

    def shared_devices(self) -> set[int]:
        """Devices hosting >= 2 queues (the arbiter's engagement set)."""
        counts: dict[int, int] = {}
        for p in self._placements.values():
            for d in p.devices:
                counts[d] = counts.get(d, 0) + 1
        return {d for d, n in counts.items() if n >= 2}

    def _validate(self, devices: Iterable[int]) -> tuple[int, ...]:
        devs = tuple(int(d) for d in devices)
        if not devs:
            raise PlacementError("a placement needs >= 1 device")
        if len(set(devs)) != len(devs):
            raise PlacementError(f"duplicate device in target {devs}")
        bad = [d for d in devs if not 0 <= d < self.n_devices]
        if bad:
            raise PlacementError(
                f"device(s) {bad} outside inventory [0, {self.n_devices})")
        return devs

    # ---- the migration typestate ------------------------------------------

    def begin(self, kind: str, queue: str, devices: Iterable[int],
              now: float, signals: dict[str, Any] | None = None,
              ) -> PlacementDecision:
        """Arm one placement action. Raises on a concurrent action on the
        same queue (exactly-once: the decision must be completed or failed
        before the next one arms)."""
        p = self.placement(queue)
        devs = self._validate(devices)
        if p.status != STABLE:
            raise PlacementError(
                f"queue {queue!r} already has a placement action in "
                f"flight (status {p.status})")
        if devs == p.devices:
            raise PlacementError(
                f"queue {queue!r} is already placed on {devs}")
        p.status = MIGRATING
        self._seq += 1
        d = PlacementDecision(seq=self._seq, t=now, kind=kind, queue=queue,
                              src=p.devices, dst=devs,
                              signals=dict(signals or {}))
        self.decisions.append(d)
        return d

    def complete(self, decision: PlacementDecision, now: float,
                 blackout_s: float, transferred: int,
                 detail: str = "") -> None:
        """The action landed: rebind, clear the typestate, record cost."""
        p = self.placement(decision.queue)
        p.devices = decision.dst
        p.status = STABLE
        p.generation += 1
        p.last_action_t = now
        decision.status = "applied"
        decision.blackout_s = blackout_s
        decision.transferred = transferred
        decision.detail = detail
        self.blackout_last[decision.queue] = blackout_s
        self.blackout_max[decision.queue] = max(
            self.blackout_max.get(decision.queue, 0.0), blackout_s)

    def refuse(self, kind: str, queue: str, devices: Iterable[int],
               now: float, detail: str) -> PlacementDecision:
        """Audit an action the typestate/validator REFUSED (concurrent
        action, unknown queue, bad target) without touching any binding —
        every decision lands in the ring, including the ones that never
        armed (the /debug/placement contract).  Raw target preserved
        unvalidated: the refusal may be ABOUT the target being invalid."""
        src: tuple[int, ...] = ()
        p = self._placements.get(queue)
        if p is not None:
            src = p.devices
        self._seq += 1
        d = PlacementDecision(seq=self._seq, t=now, kind=kind, queue=queue,
                              src=src, dst=tuple(int(x) for x in devices),
                              status="refused", detail=detail)
        self.decisions.append(d)
        return d

    def fail(self, decision: PlacementDecision, now: float,
             detail: str) -> None:
        """The action failed: binding unchanged, typestate cleared, the
        failure audited.  The cooldown anchor still advances — a failing
        target must not be retried every tick."""
        p = self.placement(decision.queue)
        p.status = STABLE
        p.last_action_t = now
        decision.status = "failed"
        decision.detail = detail

    # ---- observability -----------------------------------------------------

    def snapshot(self, history: int = 0) -> dict[str, Any]:
        """JSON-ready state for /debug/placement."""
        rows = [d.to_dict() for d in self.decisions]
        if history:
            rows = rows[-history:]
        return {
            "n_devices": self.n_devices,
            "bindings": {q: p.to_dict()
                         for q, p in sorted(self._placements.items())},
            "devices": {str(d): self.queues_on(d)
                        for d in range(self.n_devices)},
            "shared_devices": sorted(self.shared_devices()),
            "decisions": rows,
            "blackout_ms": {
                q: {"last": round(self.blackout_last.get(q, 0.0) * 1e3, 3),
                    "max": round(self.blackout_max.get(q, 0.0) * 1e3, 3)}
                for q in sorted(self.blackout_last)
            },
        }
