#!/usr/bin/env python
"""Offline WAL/segment inspector for the per-queue pool journal.

The on-call workflow after a crash (or a refused failover): point this at
a ``journal_dir`` and see what the disk ACTUALLY holds — per-record type
counts, the seq watermarks a replication standby would ack against, CRC
status frame by frame, and a torn-tail diagnosis (where the intact prefix
ends, how many trailing bytes a re-attaching writer would truncate).
Read-only: it never truncates, repairs, or appends.

    # one directory, every queue found in it
    python scripts/journal_dump.py /var/lib/matchmaking/journal

    # one queue, machine-readable
    python scripts/journal_dump.py /path/to/dir --queue matchmaking.search --json

    # slice the LSN window an incident bundle names (ISSUE 18): record
    # seq + type + payload size for every frame in [A, B]
    python scripts/journal_dump.py /path/to/dir --queue matchmaking.search \
        --lsn-range 120,180

Exit status is 0 when every inspected artifact is intact, 1 when any
segment has a torn tail / CRC-bad frame or any snapshot fails
verification — so the script doubles as a fleet health probe.

Importable: :func:`inspect_queue` / :func:`inspect_dir` return the same
dicts ``--json`` prints (tests/test_replication.py drives them directly).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

if __package__ is None and "matchmaking_tpu" not in sys.modules:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from matchmaking_tpu.service.replication import (  # noqa: E402
    RT_REPL_SNAPSHOT)
from matchmaking_tpu.utils.journal import (  # noqa: E402
    RT_ADMISSION, RT_ADMIT, RT_CLEAN, RT_SEGMENT, RT_TERMINAL, RT_TERMINALS,
    _verify_snapshot, journal_path, list_snapshots, read_segment)

#: Record-type names for reports (RT_SEGMENT appears only as the header).
#: Must cover every RT_* constant in the tree — the ``protocol`` rule's
#: vocabulary check enforces it.
RT_NAMES = {
    RT_SEGMENT: "segment",
    RT_ADMIT: "admit",
    RT_TERMINAL: "terminal",
    RT_ADMISSION: "admission",
    RT_CLEAN: "clean",
    RT_TERMINALS: "terminals",
    RT_REPL_SNAPSHOT: "repl_snapshot",
}


def inspect_segment(path: str) -> dict:
    """One segment file → framing report: header, per-type record counts,
    seq watermarks (min/max + contiguity gaps), torn-tail diagnosis."""
    size = os.path.getsize(path)
    try:
        header, records, torn, intact = read_segment(path)
    except ValueError as e:
        return {"path": path, "readable": False, "error": str(e),
                "bytes": size, "torn": True, "intact_bytes": 0}
    counts: dict[str, int] = {}
    seqs = []
    for seq, rtype, _payload in records:
        counts[RT_NAMES.get(rtype, f"rtype{rtype}")] = (
            counts.get(RT_NAMES.get(rtype, f"rtype{rtype}"), 0) + 1)
        seqs.append(seq)
    gaps = []
    for a, b in zip(seqs, seqs[1:]):
        if b != a + 1:
            gaps.append([a, b])
    clean = bool(records) and records[-1][1] == RT_CLEAN
    out = {
        "path": path,
        "readable": True,
        "bytes": size,
        "header": header,
        "records": len(records),
        "counts": counts,
        "seq_min": seqs[0] if seqs else 0,
        "seq_max": seqs[-1] if seqs else 0,
        "seq_gaps": gaps,
        "clean_tail": clean,
        "torn": torn,
        "intact_bytes": intact,
    }
    if torn:
        out["torn_bytes"] = size - intact
        out["diagnosis"] = (
            f"torn tail: last intact frame ends at byte {intact} of {size} "
            f"({size - intact} trailing bytes fail CRC/length — the normal "
            "post-crash shape; a re-attaching writer truncates here)")
    return out


def inspect_queue(directory: str, queue: str) -> dict:
    """Everything on disk for one queue: the live segment plus every
    compaction snapshot (newest first) with full-read verification."""
    seg_path = journal_path(directory, queue)
    report: dict = {"queue": queue, "directory": directory}
    report["segment"] = (inspect_segment(seg_path)
                         if os.path.exists(seg_path) else None)
    snaps = []
    for seq, path in list_snapshots(directory, queue):
        snaps.append({
            "path": path,
            "anchor_seq": seq,
            "bytes": os.path.getsize(path),
            "verified": _verify_snapshot(path),
        })
    report["snapshots"] = snaps
    seg = report["segment"]
    report["intact"] = (
        (seg is None or (seg["readable"] and not seg["torn"]))
        and all(s["verified"] for s in snaps))
    return report


def slice_lsn_range(directory: str, queue: str, lo: int,
                    hi: int) -> dict:
    """The live segment's records with ``lo <= seq <= hi`` — the slice an
    incident bundle's journal watermark names (``lsn_range``), so the
    forensics workflow is: read the bundle, then dump exactly that WAL
    window. Read-only, torn tails tolerated (the intact prefix is
    sliced)."""
    seg_path = journal_path(directory, queue)
    out: dict = {"queue": queue, "lsn_range": [lo, hi], "records": []}
    if not os.path.exists(seg_path):
        out["error"] = f"no segment for queue {queue!r}"
        return out
    try:
        _header, records, torn, _intact = read_segment(seg_path)
    except ValueError as e:
        out["error"] = str(e)
        return out
    out["torn"] = torn
    seqs = [seq for seq, _rtype, _payload in records]
    if seqs:
        out["segment_range"] = [min(seqs), max(seqs)]
    for seq, rtype, payload in records:
        if lo <= seq <= hi:
            out["records"].append({
                "seq": seq,
                "type": RT_NAMES.get(rtype, f"rtype{rtype}"),
                "payload_bytes": len(payload),
            })
    if not out["records"] and seqs and hi < min(seqs):
        out["note"] = (
            f"window {lo}..{hi} predates the live segment "
            f"(seq {min(seqs)}..{max(seqs)}) — compaction carried it into "
            "a snapshot; check the snapshot at or above this range")
    return out


def inspect_dir(directory: str) -> dict:
    """Every queue with artifacts under ``directory`` → its report."""
    queues: set[str] = set()
    for path in glob.glob(os.path.join(directory, "*.journal")):
        queues.add(os.path.basename(path)[:-len(".journal")])
    for path in glob.glob(os.path.join(directory, "*.snap.*.npz")):
        queues.add(os.path.basename(path).split(".snap.")[0])
    return {q: inspect_queue(directory, q) for q in sorted(queues)}


def _render(report: dict, out=sys.stdout) -> None:
    seg = report["segment"]
    print(f"queue {report['queue']!r}", file=out)
    if seg is None:
        print("  segment: (none)", file=out)
    elif not seg.get("readable"):
        print(f"  segment: UNREADABLE — {seg['error']}", file=out)
    else:
        counts = ", ".join(f"{k}={v}" for k, v in sorted(seg["counts"].items()))
        print(f"  segment: {seg['records']} records "
              f"(seq {seg['seq_min']}..{seg['seq_max']}), {counts or 'empty'}",
              file=out)
        if seg["seq_gaps"]:
            print(f"  seq gaps: {seg['seq_gaps']} (expected after "
                  "compaction carries; replay filters by seq)", file=out)
        print(f"  clean tail: {seg['clean_tail']}", file=out)
        if seg["torn"]:
            print(f"  TORN: {seg['diagnosis']}", file=out)
    for s in report["snapshots"]:
        mark = "ok" if s["verified"] else "CORRUPT (falls back)"
        print(f"  snapshot seq {s['anchor_seq']}: {s['bytes']} bytes — "
              f"{mark}", file=out)
    print(f"  intact: {report['intact']}", file=out)


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("directory", help="journal_dir to inspect")
    ap.add_argument("--queue", default="",
                    help="inspect one queue (default: every queue found)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--lsn-range", default="",
                    help="A,B — dump the records with A <= seq <= B from "
                         "the live segment (the window an incident "
                         "bundle's journal watermark names); requires "
                         "--queue")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.directory):
        sys.exit(f"not a directory: {args.directory}")
    if args.lsn_range:
        if not args.queue:
            sys.exit("--lsn-range requires --queue")
        try:
            lo_s, hi_s = args.lsn_range.split(",", 1)
            lo, hi = int(lo_s), int(hi_s)
        except ValueError:
            sys.exit(f"--lsn-range wants A,B integers, got "
                     f"{args.lsn_range!r}")
        sliced = slice_lsn_range(args.directory, args.queue, lo, hi)
        if args.as_json:
            json.dump(sliced, sys.stdout, indent=2, sort_keys=True)
            print()
        else:
            print(f"queue {args.queue!r} LSN range {lo}..{hi}:")
            if sliced.get("error"):
                print(f"  error: {sliced['error']}")
            for rec in sliced["records"]:
                print(f"  seq {rec['seq']:<8} {rec['type']:<10} "
                      f"{rec['payload_bytes']} bytes")
            print(f"  {len(sliced['records'])} record(s) in range"
                  + ("  [torn tail]" if sliced.get("torn") else ""))
            if sliced.get("note"):
                print(f"  note: {sliced['note']}")
        return 0 if not sliced.get("error") else 1
    if args.queue:
        reports = {args.queue: inspect_queue(args.directory, args.queue)}
    else:
        reports = inspect_dir(args.directory)
    if args.as_json:
        json.dump(reports, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        if not reports:
            print(f"no journal artifacts under {args.directory}")
        for q in sorted(reports):
            _render(reports[q])
    return 0 if all(r["intact"] for r in reports.values()) else 1


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # downstream pager/head closed the pipe; exit quietly like other CLIs
        sys.stderr.close()
        raise SystemExit(0)
