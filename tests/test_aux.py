"""Aux subsystems (SURVEY.md §5): checkpoint/resume, invariant checking,
HTTP observability."""

import asyncio

import numpy as np
import pytest

from matchmaking_tpu.config import Config, EngineConfig, QueueConfig
from matchmaking_tpu.engine.interface import make_engine
from matchmaking_tpu.service.contract import SearchRequest
from matchmaking_tpu.utils.checkpoint import load_pool, save_pool
from matchmaking_tpu.utils.invariants import InvariantChecker, InvariantViolation


def _req(i, rating, **kw):
    return SearchRequest(id=f"p{i}", rating=float(rating), enqueued_at=0.0,
                         reply_to=f"rq.p{i}", correlation_id=f"c{i}", **kw)


class TestCheckpoint:
    @pytest.mark.parametrize("backend", ["cpu", "tpu"])
    def test_save_load_roundtrip(self, tmp_path, backend):
        cfg = Config(
            queues=(QueueConfig(rating_threshold=50.0),),
            engine=EngineConfig(backend=backend, pool_capacity=128,
                                pool_block=64, batch_buckets=(16,)),
        )
        eng = make_engine(cfg, cfg.queues[0])
        # Far-apart ratings with assorted metadata; nothing matches.
        reqs = [
            _req(0, 1000, region="eu", game_mode="ranked"),
            _req(1, 2000, rating_threshold=33.0),
            _req(2, 3000, rating_deviation=120.0),
        ]
        eng.restore(reqs, 0.0)
        path = str(tmp_path / "pool.npz")
        assert save_pool(eng, path, queue_name="q") == 3

        eng2 = make_engine(cfg, cfg.queues[0])
        assert load_pool(eng2, path, now=1.0) == 3
        assert eng2.pool_size() == 3
        by_id = {r.id: r for r in eng2.waiting()}
        assert by_id["p0"].region == "eu" and by_id["p0"].game_mode == "ranked"
        assert by_id["p1"].rating_threshold == pytest.approx(33.0)
        assert by_id["p2"].rating_deviation == pytest.approx(120.0)
        assert by_id["p0"].reply_to == "rq.p0"
        assert by_id["p0"].enqueued_at == pytest.approx(0.0)

    def test_load_is_idempotent_and_does_not_match(self, tmp_path):
        cfg = Config(
            queues=(QueueConfig(rating_threshold=100.0),),
            engine=EngineConfig(backend="tpu", pool_capacity=64,
                                pool_block=64, batch_buckets=(16,)),
        )
        eng = make_engine(cfg, cfg.queues[0])
        # A matchable pair — restore must NOT match them.
        eng.restore([_req(0, 1500), _req(1, 1501)], 0.0)
        path = str(tmp_path / "pool.npz")
        save_pool(eng, path)
        eng2 = make_engine(cfg, cfg.queues[0])
        load_pool(eng2, path, now=0.0)
        load_pool(eng2, path, now=0.0)  # idempotent: dedupe on restore
        assert eng2.pool_size() == 2
        # They match on the next real window.
        out = eng2.search([_req(9, 1502)], 1.0)
        assert len(out.matches) == 1

    def test_cross_backend_restore(self, tmp_path):
        """A CPU-oracle checkpoint restores into the TPU engine (portable
        format: region/mode by name)."""
        cfg_c = Config(queues=(QueueConfig(),))
        cpu = make_engine(cfg_c, cfg_c.queues[0])
        cpu.restore([_req(0, 1200, region="na"), _req(1, 4000)], 0.0)
        path = str(tmp_path / "pool.npz")
        save_pool(cpu, path)

        cfg_t = Config(queues=(QueueConfig(),),
                       engine=EngineConfig(backend="tpu", pool_capacity=64,
                                           pool_block=64, batch_buckets=(16,)))
        tpu = make_engine(cfg_t, cfg_t.queues[0])
        load_pool(tpu, path, now=0.0)
        assert tpu.pool_size() == 2
        out = tpu.search([_req(5, 1201, region="na")], 1.0)
        assert len(out.matches) == 1


class TestInvariantChecker:
    def test_double_match_detected(self):
        inv = InvariantChecker()
        inv.observe_match("m1", (("a",), ("b",)))
        with pytest.raises(InvariantViolation):
            inv.observe_match("m2", (("a",), ("c",)))

    def test_requeue_releases_hold(self):
        inv = InvariantChecker()
        inv.observe_match("m1", (("a",), ("b",)))
        inv.observe_queued("a")
        inv.observe_match("m2", (("a",), ("c",)))  # fine after requeue

    def test_duplicate_in_one_match(self):
        inv = InvariantChecker()
        with pytest.raises(InvariantViolation):
            inv.observe_match("m1", (("a",), ("a",)))

    def test_team_size_enforced(self):
        inv = InvariantChecker(team_size=2)
        with pytest.raises(InvariantViolation):
            inv.observe_match("m1", (("a", "b"), ("c",)))

    def test_columnar_outcome_observed(self):
        from matchmaking_tpu.engine.interface import empty_columnar_outcome

        out = empty_columnar_outcome()
        out.m_id_a = np.asarray(["a"], object)
        out.m_id_b = np.asarray(["b"], object)
        out.m_match_id = np.asarray(["m1"], object)
        inv = InvariantChecker()
        inv.observe_outcome(out)
        with pytest.raises(InvariantViolation):
            inv.observe_match("m2", (("b",), ("z",)))


class TestObservability:
    def test_healthz_and_metrics(self):
        import aiohttp

        from matchmaking_tpu.service.app import MatchmakingApp

        async def run():
            cfg = Config(metrics_port=19155, debug_invariants=True)
            app = MatchmakingApp(cfg)
            await app.start()
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.get("http://127.0.0.1:19155/healthz") as r:
                        body = await r.json()
                        assert body["status"] == "ok"
                        assert "matchmaking.search" in body["queues"]
                    async with s.get("http://127.0.0.1:19155/metrics") as r:
                        report = await r.json()
                        assert "counters" in report and "pools" in report
                    async with s.get(
                            "http://127.0.0.1:19155/metrics?format=prom") as r:
                        text = await r.text()
                        assert "matchmaking_pool_size" in text
            finally:
                await app.stop()

        asyncio.run(run())


class TestAppCheckpointIntegration:
    def test_save_restore_via_app(self, tmp_path):
        from matchmaking_tpu.service.app import MatchmakingApp
        from matchmaking_tpu.service.client import MatchmakingClient

        async def run():
            cfg = Config(queues=(QueueConfig(rating_threshold=1.0),))
            app = MatchmakingApp(cfg)
            await app.start()
            client = MatchmakingClient(app.broker, cfg.broker.request_queue)
            # Two players that cannot match (threshold 1, distance 100).
            rt_a = client.submit({"id": "a", "rating": 1000})
            rt_b = client.submit({"id": "b", "rating": 1100})
            r1 = await client.next_response(rt_a, timeout=2.0)
            r2 = await client.next_response(rt_b, timeout=2.0)
            assert r1.status == "queued" and r2.status == "queued"
            counts = await app.save_checkpoint(str(tmp_path / "ckpt"))
            assert counts == {"matchmaking.search": 2}
            await app.stop()

            app2 = MatchmakingApp(Config(queues=(QueueConfig(rating_threshold=1.0),)))
            await app2.start()
            counts = await app2.restore_checkpoint(str(tmp_path / "ckpt"))
            assert counts == {"matchmaking.search": 2}
            rt = app2.runtime("matchmaking.search")
            assert rt.engine.pool_size() == 2
            await app2.stop()

        asyncio.run(run())
